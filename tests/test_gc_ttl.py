"""TTL garbage-collector tables.

The analog of ``pkg/controllers/garbagecollector/garbagecollector_test.go``
(ProcessTTL / NeedsCleanup / IsJobFinished tables), driven against the
sweep with an injected clock so expiry is deterministic.
"""

import pytest

from volcano_tpu.api import Node, PodGroupPhase
from volcano_tpu.cache import ClusterStore
from volcano_tpu.controllers import Job, JobController, TaskSpec
from volcano_tpu.controllers.apis import JobPhase, VolumeSpec
from volcano_tpu.controllers.gc import FINISHED, GarbageCollector


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def finished_job(name="j1", ttl=3, phase=JobPhase.Completed.value,
                 finish_time=1000.0):
    job = Job(name=name, min_available=1,
              tasks=[TaskSpec(name="w", replicas=1,
                              containers=[{"cpu": "1"}])],
              ttl_seconds_after_finished=ttl)
    job.status.state.phase = phase
    job.status.state.last_transition = finish_time
    return job


# --------------------------------------------------------- phase tables


@pytest.mark.parametrize("phase,is_finished", [
    (JobPhase.Completed.value, True),
    (JobPhase.Failed.value, True),
    (JobPhase.Terminated.value, True),
    (JobPhase.Pending.value, False),
    (JobPhase.Running.value, False),
    (JobPhase.Aborted.value, False),
    (JobPhase.Restarting.value, False),
])
def test_is_job_finished_table(phase, is_finished):
    """IsJobFinished: only Completed/Failed/Terminated count as
    finished (garbagecollector.go isJobFinished)."""
    assert (phase in FINISHED) == is_finished


@pytest.mark.parametrize("ttl,phase,collected", [
    # needsCleanup: finished + TTL set -> cleanup candidate.
    (3, JobPhase.Completed.value, True),
    (3, JobPhase.Failed.value, True),
    (3, JobPhase.Terminated.value, True),
    # Running jobs are never TTL-collected regardless of TTL.
    (3, JobPhase.Running.value, False),
    (0, JobPhase.Running.value, False),
    # TTL unset -> never collected even when finished.
    (None, JobPhase.Completed.value, False),
])
def test_needs_cleanup_table(ttl, phase, collected):
    store = ClusterStore()
    clock = Clock(2000.0)
    gc = GarbageCollector(store, clock=clock)
    job = finished_job(ttl=ttl, phase=phase, finish_time=1000.0)
    store.batch_jobs[job.key] = job
    n = gc.sweep()
    assert (n == 1) == collected
    assert (job.key not in store.batch_jobs) == collected


# ------------------------------------------------------------ processTTL


def test_ttl_not_yet_expired_false_case():
    """ProcessTTL "False Case": ttl=3 with a fresh finish -> kept."""
    store = ClusterStore()
    clock = Clock(1001.0)  # 1s after finish, ttl 3s
    gc = GarbageCollector(store, clock=clock)
    job = finished_job(ttl=3, finish_time=1000.0)
    store.batch_jobs[job.key] = job
    assert gc.sweep() == 0
    assert job.key in store.batch_jobs


def test_ttl_zero_expires_immediately_true_case():
    """ProcessTTL "True Case": ttl=0 -> expired the moment it finishes."""
    store = ClusterStore()
    clock = Clock(1000.0)
    gc = GarbageCollector(store, clock=clock)
    job = finished_job(ttl=0, finish_time=1000.0)
    store.batch_jobs[job.key] = job
    assert gc.sweep() == 1
    assert job.key not in store.batch_jobs


def test_ttl_expires_after_clock_advance():
    store = ClusterStore()
    clock = Clock(1001.0)
    gc = GarbageCollector(store, clock=clock)
    job = finished_job(ttl=3, finish_time=1000.0)
    store.batch_jobs[job.key] = job
    assert gc.sweep() == 0
    clock.t = 1003.5
    assert gc.sweep() == 1


def test_unfinished_job_resets_observed_finish_time():
    """A job that left the finished phase (restart) must not be
    collected from a stale finish timestamp when it finishes again."""
    store = ClusterStore()
    clock = Clock(1000.0)
    gc = GarbageCollector(store, clock=clock)
    job = finished_job(ttl=3, finish_time=999.0)
    store.batch_jobs[job.key] = job
    assert gc.sweep() == 0  # records finish at 999; not yet expired
    # Restart: phase leaves FINISHED; the observed finish time clears.
    job.status.state.phase = JobPhase.Running.value
    clock.t = 2000.0
    assert gc.sweep() == 0
    # Finishes again at 2000 (no last_transition update -> sweep uses
    # observation time); ttl counts from the NEW finish.
    job.status.state.phase = JobPhase.Completed.value
    job.status.state.last_transition = 2000.0
    clock.t = 2001.0
    assert gc.sweep() == 0  # only 1s since the new finish
    clock.t = 2004.0
    assert gc.sweep() == 1


def test_sweep_collects_multiple_and_skips_ttl_less():
    store = ClusterStore()
    clock = Clock(5000.0)
    gc = GarbageCollector(store, clock=clock)
    for i, ttl in enumerate((1, 1, None)):
        job = finished_job(name=f"j{i}", ttl=ttl, finish_time=1000.0)
        store.batch_jobs[job.key] = job
    assert gc.sweep() == 2
    assert list(store.batch_jobs) == ["default/j2"]


# -------------------------------------------------- cascading deletion


def test_ttl_delete_cascades_pods_podgroup_and_claims():
    """delete_batch_job through the TTL sweep reaps the job's pods,
    PodGroup, and controller-owned claims (owner-reference cascade)."""
    store = ClusterStore()
    store.add_node(Node(name="n0",
                        allocatable={"cpu": "8", "memory": "16Gi"}))
    jc = JobController(store)
    job = Job(name="j1", min_available=1,
              tasks=[TaskSpec(name="w", replicas=2,
                              containers=[{"cpu": "1", "memory": "1Gi"}])],
              volumes=[VolumeSpec(mount_path="/data",
                                  volume_claim={"storage": "1Gi"})],
              ttl_seconds_after_finished=1)
    store.add_batch_job(job)
    jc.process_all()
    pg = store.pod_groups["default/j1"]
    pg.status.phase = PodGroupPhase.Inqueue.value
    store.update_pod_group(pg)
    jc.process_all()
    jc.sync_job(job, None)
    assert len([p for p in store.pods.values()
                if p.owner_job == job.key]) == 2
    assert len(store.pvcs) == 1

    job.status.state.phase = JobPhase.Completed.value
    job.status.state.last_transition = 1000.0
    clock = Clock(5000.0)
    gc = GarbageCollector(store, clock=clock)
    assert gc.sweep() == 1
    jc.process_all()  # the delete event pumps the controller cleanup
    assert "default/j1" not in store.batch_jobs
    assert "default/j1" not in store.pod_groups
    assert all(p.deleting for p in store.pods.values()
               if p.owner_job == "default/j1")
    assert not store.pvcs  # owned claim reaped


def test_sweep_uses_last_transition_when_present():
    """The reference counts TTL from the job's LastTransitionTime; the
    sweep honors it when set instead of its own observation time."""
    store = ClusterStore()
    clock = Clock(1010.0)
    gc = GarbageCollector(store, clock=clock)
    job = finished_job(ttl=5, finish_time=1000.0)  # finished 10s ago
    store.batch_jobs[job.key] = job
    # First sweep already sees it expired (1010 - 1000 >= 5).
    assert gc.sweep() == 1
