"""Array schema / snapshot encoder tests: the device mirror must agree with
the host data model."""

import numpy as np

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    MIN_MEMORY,
    MIN_MILLI_CPU,
    Node,
    Pod,
    PodGroup,
    PodPhase,
    Taint,
    TaskStatus,
    Toleration,
)
from volcano_tpu.arrays import ResourceSlots, encode_cluster
from volcano_tpu.cache import ClusterStore


def make_cluster():
    store = ClusterStore()
    store.add_node(
        Node(
            name="n1",
            allocatable={"cpu": "4", "memory": "8Gi", "pods": 110},
            labels={"zone": "a"},
        )
    )
    store.add_node(
        Node(
            name="n2",
            allocatable={"cpu": "8", "memory": "16Gi", "pods": 110},
            labels={"zone": "b"},
            taints=[Taint(key="dedicated", value="ml", effect="NoSchedule")],
        )
    )
    store.add_pod_group(PodGroup(name="pg1", min_member=2))
    for i in range(3):
        store.add_pod(
            Pod(
                name=f"p{i}",
                annotations={GROUP_NAME_ANNOTATION: "pg1"},
                containers=[{"cpu": "1", "memory": "1Gi"}],
                node_selector={"zone": "a"} if i == 0 else {},
                tolerations=[
                    Toleration(key="dedicated", operator="Equal", value="ml",
                               effect="NoSchedule")
                ]
                if i == 2
                else [],
            )
        )
    return store


def encode(store):
    snap = store.snapshot()
    job = snap.jobs["default/pg1"]
    pending = sorted(
        job.task_status_index[TaskStatus.Pending].values(), key=lambda t: t.name
    )
    return encode_cluster(snap, pending, ["default/pg1"])


def test_encode_shapes_and_values():
    arrays, maps = encode(make_cluster())
    R = maps.slots.width
    assert R == 2  # cpu, memory only
    n1 = maps.node_index["n1"]
    assert arrays.nodes.idle[n1, 0] == 4000
    assert arrays.nodes.idle[n1, 1] == 8 * 1024**3
    assert arrays.nodes.max_tasks[n1] == 110
    assert arrays.nodes.real.sum() == 2
    assert arrays.tasks.real.sum() == 3
    assert arrays.jobs.min_available[0] == 2
    # eps vector carries the Go quanta.
    assert arrays.eps[0] == MIN_MILLI_CPU
    assert arrays.eps[1] == MIN_MEMORY


def test_label_bitsets_match_selectors():
    arrays, maps = encode(make_cluster())
    n1, n2 = maps.node_index["n1"], maps.node_index["n2"]
    # p0 requires zone=a: its selector bits must be subset of n1's labels only.
    p0 = maps.task_uids.index(
        next(t.uid for t in maps.task_infos if t.name == "p0")
    )
    sel = arrays.tasks.sel_bits[p0]
    assert arrays.tasks.has_selector[p0]
    assert np.all((sel & ~arrays.nodes.label_bits[n1]) == 0)
    assert not np.all((sel & ~arrays.nodes.label_bits[n2]) == 0)


def test_taint_toleration_bits():
    arrays, maps = encode(make_cluster())
    n2 = maps.node_index["n2"]
    # n2 has one gating taint bit.
    assert arrays.nodes.taint_bits[n2].sum() > 0
    p2 = maps.task_uids.index(
        next(t.uid for t in maps.task_infos if t.name == "p2")
    )
    p1 = maps.task_uids.index(
        next(t.uid for t in maps.task_infos if t.name == "p1")
    )
    # p2 tolerates the taint; p1 does not.
    assert np.all((arrays.nodes.taint_bits[n2] & ~arrays.tasks.tol_bits[p2]) == 0)
    assert not np.all(
        (arrays.nodes.taint_bits[n2] & ~arrays.tasks.tol_bits[p1]) == 0
    )


def test_scalar_slots():
    store = make_cluster()
    store.add_node(
        Node(name="g1", allocatable={"cpu": "4", "memory": "8Gi",
                                     "nvidia.com/gpu": 8})
    )
    arrays, maps = encode(store)
    assert maps.slots.width == 3
    g1 = maps.node_index["g1"]
    gpu_slot = maps.slots.index["nvidia.com/gpu"]
    assert arrays.nodes.idle[g1, gpu_slot] == 8000
    assert bool(arrays.scalar_slot[gpu_slot])
    assert not bool(arrays.scalar_slot[0])
