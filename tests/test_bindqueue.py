"""Async bind dispatch + rate-limited bind-failure backoff + event trail
(the analog of cache.go:536-552 goroutine binds and 627-649 errTasks)."""

import time

from volcano_tpu.cache.interface import BindFailure
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.synth import synthetic_cluster


def _flaky(store, fail_times):
    """Wrap the store's binder: the first ``fail_times`` batches fail the
    second half of their keys."""
    orig = store.binder.bind_keys
    state = {"left": fail_times}

    def flaky(keys, hosts):
        if state["left"] > 0:
            state["left"] -= 1
            half = len(keys) // 2
            orig(list(keys[:half]), list(hosts[:half]))
            raise BindFailure(list(keys[half:]))
        orig(keys, hosts)

    store.binder.bind_keys = flaky
    return state


def test_async_bind_failure_reverts_with_backoff(monkeypatch):
    from volcano_tpu.cache import bindqueue

    monkeypatch.setattr(bindqueue, "BACKOFF_BASE", 0.05)
    store = synthetic_cluster(n_nodes=8, n_pods=24, gang_size=1)
    store.async_bind = True
    _flaky(store, fail_times=1)
    sched = Scheduler(store)
    sched.run_once()
    assert store.flush_binds(timeout=10)
    assert len(store.binder.binds) == 12

    # Next cycle drains the failures: tasks revert to Pending, carry a
    # backoff window, and are NOT re-solved within it.
    sched.run_once()
    assert store.flush_binds(timeout=10)
    assert len(store.bind_backoff) == 12
    assert len(store.binder.binds) == 12  # still inside backoff

    # FailedScheduling events are visible on the pods.
    failed_keys = list(store.bind_backoff)
    evs = store.events_for(f"Pod/{failed_keys[0]}")
    assert any(e["reason"] == "FailedScheduling" for e in evs)

    # After the backoff expires the tasks re-enter and bind.
    time.sleep(0.12)
    sched.run_once()
    assert store.flush_binds(timeout=10)
    assert len(store.binder.binds) == 24
    assert all(p.node_name for p in store.pods.values())
    # Successful rebind clears the backoff state at the next cycle's
    # drain (clears are queued for the cycle thread, which owns
    # bind_backoff — store._on_bind_success).
    sched.run_once()
    assert not store.bind_backoff


def test_async_bind_success_records_scheduled_events():
    store = synthetic_cluster(n_nodes=4, n_pods=8, gang_size=1)
    store.async_bind = True
    Scheduler(store).run_once()
    assert store.flush_binds(timeout=10)
    pod = next(iter(store.pods.values()))
    evs = store.events_for(f"Pod/{pod.namespace}/{pod.name}")
    assert any(e["reason"] == "Scheduled" for e in evs)


def test_unschedulable_gang_records_podgroup_event():
    # A gang that cannot fit leaves an Unschedulable event on its group.
    store = synthetic_cluster(n_nodes=1, n_pods=4, gang_size=4,
                              pod_cpu_choices=("64",),
                              pod_mem_choices=("256Gi",))
    Scheduler(store).run_once()
    pgs = [pg for pg in store.pod_groups.values()]
    assert pgs
    hit = False
    for pg in pgs:
        evs = store.events_for(f"PodGroup/{pg.namespace}/{pg.name}")
        if any(e["reason"] == "Unschedulable" for e in evs):
            hit = True
    assert hit


def test_evict_records_event():
    from volcano_tpu.synth import preempt_cluster

    store = preempt_cluster(n_nodes=4, fill_per_node=4, n_pending=8,
                            gang_size=1)
    conf = """
actions: "enqueue, allocate, preempt, reclaim, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""
    Scheduler(store, conf_str=conf).run_once()
    evicted = getattr(store.evictor, "evicts", [])
    assert evicted
    key = evicted[0]
    evs = store.events_for(f"Pod/{key}")
    assert any(e["reason"] == "Evict" for e in evs)


def test_indeterminate_batch_exception_redrives_per_key():
    """A non-BindFailure exception from bind_keys must not fail the whole
    batch: binds that already landed would be re-queued and later re-bound
    (possibly to a different node).  The dispatcher re-drives per key
    instead (bindqueue.py worker)."""
    store = synthetic_cluster(n_nodes=8, n_pods=16, gang_size=1)
    store.async_bind = True
    orig = store.binder.bind_keys
    state = {"left": 1}

    def broken(keys, hosts):
        if state["left"] > 0:
            state["left"] -= 1
            half = len(keys) // 2
            orig(list(keys[:half]), list(hosts[:half]))
            raise RuntimeError("transport blew up mid-batch")
        orig(keys, hosts)

    store.binder.bind_keys = broken
    sched = Scheduler(store)
    sched.run_once()
    assert store.flush_binds(timeout=10)
    # Per-key re-drive landed every bind exactly where the solver put it:
    # no pod re-entered Pending, no backoff, all 16 bound.
    assert len(store.binder.binds) == 16
    sched.run_once()
    assert not store.bind_backoff
    assert all(p.node_name for p in store.pods.values())


def test_deleted_pod_prunes_backoff_entry(monkeypatch):
    from volcano_tpu.cache import bindqueue

    monkeypatch.setattr(bindqueue, "BACKOFF_BASE", 60.0)
    store = synthetic_cluster(n_nodes=8, n_pods=8, gang_size=1)
    store.async_bind = True
    _flaky(store, fail_times=1)
    sched = Scheduler(store)
    sched.run_once()
    assert store.flush_binds(timeout=10)
    sched.run_once()  # drain failures -> backoff entries
    assert store.bind_backoff
    key = next(iter(store.bind_backoff))
    ns, name = key.split("/", 1)
    pod = next(p for p in store.pods.values()
               if p.namespace == ns and p.name == name)
    store.delete_pod(pod)
    assert key not in store.bind_backoff


def test_bind_failure_releases_claim_pin(monkeypatch):
    """A claim provisioned for a pod whose bind then fails must return
    to Pending (unpinned) so the retry can place the pod — and the
    claim — on any node."""
    from volcano_tpu.api import GROUP_NAME_ANNOTATION, Node, Pod, PodGroup
    from volcano_tpu.cache import ClusterStore
    from volcano_tpu.cache import bindqueue

    monkeypatch.setattr(bindqueue, "BACKOFF_BASE", 0.05)
    store = ClusterStore()
    store.add_node(Node(name="n0", allocatable={"cpu": "8",
                                                "memory": "16Gi"}))
    store.add_node(Node(name="n1", allocatable={"cpu": "8",
                                                "memory": "16Gi"}))
    store.put_pvc("default", "claim", {"storage": "1Gi"})
    store.add_pod_group(PodGroup(name="g", min_member=1))
    store.add_pod(Pod(
        name="p0",
        containers=[{"cpu": "1", "memory": "1Gi"}],
        annotations={GROUP_NAME_ANNOTATION: "g"},
        volumes=[("claim", "/data")],
    ))
    store.async_bind = True
    _flaky(store, fail_times=1)  # fails the 2nd half => our only pod?
    # _flaky fails keys[half:]; with one key, half=0 -> all fail.
    sched = Scheduler(store)
    sched.run_once()
    assert store.flush_binds(timeout=10)
    sched.run_once()  # drain: pod back to Pending with backoff
    pod = next(iter(store.pods.values()))
    assert pod.node_name is None
    rec = store.pvcs["default/claim"]
    assert rec["phase"] == "Pending" and rec["node"] is None

    import time as _t
    _t.sleep(0.12)
    sched.run_once()
    assert store.flush_binds(timeout=10)
    pod = next(iter(store.pods.values()))
    assert pod.node_name is not None
    assert store.pvcs["default/claim"]["phase"] == "Bound"
    assert store.pvcs["default/claim"]["node"] == pod.node_name


# ------------------------------------------------- churn stress (r4)


def test_dispatcher_vs_store_churn_stress(monkeypatch):
    """Concurrent async-bind dispatch, bind failures, pod deletions and
    re-adds, and cycle-thread drains: no deadlock, no lost pods, and
    every surviving pod either binds or re-enters Pending with backoff.
    The bindqueue race surface VERDICT r3 called thin, exercised
    directly."""
    import threading

    from volcano_tpu.api import GROUP_NAME_ANNOTATION, Pod, PodGroup
    from volcano_tpu.cache import bindqueue

    monkeypatch.setattr(bindqueue, "BACKOFF_BASE", 0.02)
    store = synthetic_cluster(n_nodes=16, n_pods=64, gang_size=1, seed=5)
    store.async_bind = True
    # Every third batch fails its second half.
    orig = store.binder.bind_keys
    calls = {"n": 0}

    def flaky(keys, hosts):
        calls["n"] += 1
        if calls["n"] % 3 == 0:
            half = len(keys) // 2
            orig(list(keys[:half]), list(hosts[:half]))
            raise BindFailure(list(keys[half:]))
        orig(keys, hosts)

    store.binder.bind_keys = flaky
    sched = Scheduler(store)
    stop = threading.Event()
    errors = []

    def churner():
        """Deletes and re-adds pods while cycles and binds run.
        Iteration-bounded, not wall-clock-bounded: surviving churn pods
        must stay well under cluster capacity or unschedulable pods
        (neither bound nor backed off) would flake the final assert on
        fast machines."""
        i = 0
        try:
            while not stop.is_set() and i < 400:
                i += 1
                name = f"churn-{i}"
                pg = PodGroup(name=name, min_member=1)
                store.add_pod_group(pg)
                pod = Pod(
                    name=f"{name}-0",
                    annotations={GROUP_NAME_ANNOTATION: name},
                    containers=[{"cpu": "1", "memory": "1Gi"}],
                )
                store.add_pod(pod)
                time.sleep(0.002)
                if i % 2 == 0:
                    store.delete_pod(pod)
                    store.delete_pod_group(f"default/{name}")
        except Exception as e:  # pragma: no cover - failure channel
            errors.append(e)

    t = threading.Thread(target=churner)
    t.start()
    try:
        deadline = time.time() + 4.0
        while time.time() < deadline:
            sched.run_once()
            time.sleep(0.01)
    finally:
        stop.set()
        t.join(timeout=10)
    assert not t.is_alive()
    assert not errors, errors
    assert store.flush_binds(timeout=30)
    # Converge: backoff windows expire, the remaining pods bind.
    time.sleep(0.1)
    for _ in range(6):
        sched.run_once()
        store.flush_binds(timeout=30)
        time.sleep(0.03)
    store.close()
    unbound = [
        f"{p.namespace}/{p.name}" for p in store.pods.values()
        if p.node_name is None and not p.deleting
    ]
    # Everything alive is either bound or still inside a backoff window.
    for key in unbound:
        assert key in store.bind_backoff, (
            f"{key} neither bound nor backed off "
            f"(backoff={list(store.bind_backoff)[:5]}...)"
        )
    # Binder-side state agrees with the pod records for bound pods.
    for p in store.pods.values():
        if p.node_name is not None:
            key = f"{p.namespace}/{p.name}"
            assert store.binder.binds.get(key) == p.node_name


def test_flush_timeout_returns_false_on_wedged_binder():
    """flush(timeout) must not hang when a binder stalls."""
    import threading

    from volcano_tpu.cache.bindqueue import BindDispatcher

    release = threading.Event()

    class Wedged:
        def bind_keys(self, keys, hosts):
            release.wait(10)

    d = BindDispatcher(Wedged(), lambda pairs: None)
    d.dispatch(["a/b"], ["n0"], [None])
    t0 = time.time()
    assert d.flush(timeout=0.2) is False
    assert time.time() - t0 < 5
    release.set()
    assert d.flush(timeout=10) is True
    d.stop()


def test_deferred_record_walk_sets_node_name_post_cycle():
    """Async watcher-free cycles ship the bind batch as object arrays;
    the dispatcher worker applies the pod.node_name record walk
    post-cycle (the reference's API-server-side NodeName write,
    cache.go:536-552).  After flush, every bound pod record must carry
    its host and the binder must have seen every key."""
    store = synthetic_cluster(n_nodes=4, n_pods=32, gang_size=4, seed=5)
    store.async_bind = True
    Scheduler(store).run_once()
    assert store.flush_binds(timeout=30)
    assert len(store.binder.binds) == 32
    named = [p for p in store.pods.values() if p.node_name]
    assert len(named) == 32
    store.close()


def test_deferred_record_walk_applies_before_failure_resync():
    """A cycle that fails after commit must apply the deferred record
    walk before the mirror resync, or committed pods would read as
    unbound and double-schedule (fastpath.run() exception path)."""
    import pytest

    from volcano_tpu.fastpath import FastCycle

    store = synthetic_cluster(n_nodes=4, n_pods=32, gang_size=4, seed=6)
    store.async_bind = True
    orig = FastCycle._close

    def boom(self):
        raise RuntimeError("injected close failure")

    FastCycle._close = boom
    try:
        with pytest.raises(RuntimeError, match="injected"):
            Scheduler(store).run_once()
    finally:
        FastCycle._close = orig
    # The exception path applied the record walk synchronously.
    named = [p for p in store.pods.values() if p.node_name]
    assert len(named) == 32
    store.flush_binds(timeout=30)
    store.close()


def test_apply_pending_bind_records_covers_undispatched_batches():
    """Deferred record walks register with the STORE at commit time, so
    a failure path can force them even when the dispatcher worker has
    not processed the batch yet (prior-cycle coverage)."""
    store = synthetic_cluster(n_nodes=4, n_pods=32, gang_size=4, seed=7)
    store.async_bind = True
    Scheduler(store).run_once()
    # Do NOT flush: force synchronously, racing (idempotently) with the
    # worker thread.
    store.apply_pending_bind_records()
    named = [p for p in store.pods.values() if p.node_name]
    assert len(named) == 32
    store.flush_binds(timeout=30)
    assert len(store.binder.binds) == 32
    store.close()


def test_materialize_bind_entry_removes_by_identity():
    """Regression (ISSUE 9 satellite): ``_materialize_bind_entry`` used
    ``list.remove``, whose == scan compares this entry against OTHER
    pending entries — and two entries holding numpy object arrays raise
    the ambiguous-truth ValueError mid-scan, which the old handler
    swallowed.  The materialized entry then stayed registered forever
    and ``apply_pending_bind_records`` (which loops until the list
    drains) never terminated.  Removal is now by identity."""
    import numpy as np

    from volcano_tpu.cache import ClusterStore

    class Rec:
        node_name = None

    store = ClusterStore()

    def batch(n, tag):
        keys = np.array([f"default/{tag}-{i}" for i in range(n)],
                        dtype=object)
        hosts = np.array([f"n{i}" for i in range(n)], dtype=object)
        pods = np.array([Rec() for _ in range(n)], dtype=object)
        return keys, hosts, pods

    e1 = store.defer_bind_records(*batch(3, "a"))
    e2 = store.defer_bind_records(*batch(3, "b"))
    # Materialize the SECOND entry first: the removal scan compares it
    # against e1 (numpy object arrays on both sides) before reaching
    # e2 — exactly the ambiguous-truth trap.
    keys, hosts, pods = store._materialize_bind_entry(e2)
    assert keys == ["default/b-0", "default/b-1", "default/b-2"]
    assert [p.node_name for p in pods] == ["n0", "n1", "n2"]
    # The entry must be GONE (by identity) — pre-fix it was stranded
    # with entry[3] already True, the unbounded-loop condition.
    assert not any(e is e2 for e in store._pending_record_walks)
    # And the drain loop terminates, applying the remaining batch.
    store.apply_pending_bind_records()
    assert store._pending_record_walks == []
    assert not any(e is e1 for e in store._pending_record_walks)
    assert e1[3] is True
    store.close()
