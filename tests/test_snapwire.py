"""Wire-frame codec unit tests (csrc/vcsnap.cc vcsnap_frame_* +
cache/snapwire.py): roundtrip fidelity, native/numpy layout parity,
hostile-input rejection."""

import numpy as np
import pytest

from volcano_tpu.cache import snapwire as sw


def _cases():
    rng = np.random.RandomState(7)
    return [
        np.arange(12, dtype=np.float32).reshape(3, 4),
        np.array([], np.int16),
        rng.randint(0, 255, (5, 2, 3)).astype(np.uint8),
        np.array(True),  # 0-dim
        rng.standard_normal((7,)).astype(np.float64),
        np.array([[1, -2], [3, 4]], np.int64),
        np.zeros((2, 0, 3), np.int32),  # zero-size middle dim
    ]


def test_roundtrip_native_or_fallback():
    arrays = _cases()
    man = {"op": "solve", "k": [1, 2.5, "x"], "wave": None}
    buf = sw.encode_frame(arrays, man)
    m2, arrs2 = sw.decode_frame(buf)
    assert m2 == man
    for a, b in zip(arrays, arrs2):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert np.array_equal(a, b)


def test_fallback_layout_byte_identical(monkeypatch):
    arrays = _cases()
    man = {"m": "x"}
    native = sw.encode_frame(arrays, man)
    monkeypatch.setattr(sw, "lib_or_none", lambda: None)
    fallback = sw.encode_frame(arrays, man)
    assert native == fallback
    m, arrs = sw.decode_frame(native)  # fallback parser reads native frame
    assert m == man and all(
        np.array_equal(a, b) for a, b in zip(arrays, arrs)
    )


@pytest.mark.parametrize("use_native", [True, False])
def test_malformed_frames_rejected(monkeypatch, use_native):
    if not use_native:
        monkeypatch.setattr(sw, "lib_or_none", lambda: None)
    good = sw.encode_frame([np.arange(4, dtype=np.int32)], {})
    with pytest.raises(ValueError):
        sw.decode_frame(b"nope")
    with pytest.raises(ValueError):
        sw.decode_frame(good[:20])  # truncated mid-headers
    bad_magic = b"XXXX" + good[4:]
    with pytest.raises(ValueError):
        sw.decode_frame(bad_magic)


def test_tree_flatten_roundtrip():
    from volcano_tpu.ops.allocate import SolveJobs

    arrays: list = []
    tree = sw.flatten_tree(
        (SolveJobs(queue=np.zeros(3, np.int32),
                   min_available=np.ones(3, np.int32),
                   ready_base=np.zeros(3, np.int32)),
         None, 2.5, "s", (np.array([1.0], np.float32),)),
        arrays,
    )
    out = sw.unflatten_tree(tree, arrays, {"SolveJobs": SolveJobs})
    jobs, none_v, f, s, tup = out
    assert isinstance(jobs, SolveJobs) and none_v is None
    assert f == 2.5 and s == "s"
    assert np.array_equal(tup[0], [1.0])


@pytest.mark.parametrize("use_native", [True, False])
def test_hostile_count_and_dtype_rejected(monkeypatch, use_native):
    """A corrupt header must not size allocations (huge array count) or
    index dtype tables (out-of-range code) — both parsers reject with
    ValueError before touching memory."""
    if not use_native:
        monkeypatch.setattr(sw, "lib_or_none", lambda: None)
    # magic+version intact, n_arrays = 0x7FFFFFFF, no manifest
    evil = np.array([0x4E534356, 1, 0x7FFFFFFF, 0], np.uint32).tobytes()
    with pytest.raises(ValueError):
        sw.decode_frame(evil)
    good = bytearray(sw.encode_frame([np.arange(4, dtype=np.int32)], {}))
    good[16] = 200  # dtype code out of range
    with pytest.raises(ValueError):
        sw.decode_frame(bytes(good))


@pytest.mark.parametrize("use_native", [True, False])
def test_dims_nbytes_mismatch_rejected(monkeypatch, use_native):
    """A corrupt dim that disagrees with the recorded byte length must
    not decode into a view bleeding into the next array's bytes."""
    if not use_native:
        monkeypatch.setattr(sw, "lib_or_none", lambda: None)
    good = bytearray(sw.encode_frame(
        [np.arange(8, dtype=np.int32).reshape(2, 4),
         np.arange(6, dtype=np.int32)], {}))
    # First array header starts after the 16-byte frame header plus the
    # manifest ("{}" = 2 bytes) padded to 8; dims are at +8 within it.
    # Double dim0 from 2 to 4.
    man_len = len(b"{}")
    d0 = ((16 + man_len + 7) & ~7) + 8
    dim0 = np.frombuffer(bytes(good[d0:d0 + 8]), np.int64)[0]
    assert dim0 == 2
    good[d0:d0 + 8] = np.int64(4).tobytes()
    with pytest.raises(ValueError):
        sw.decode_frame(bytes(good))
    # Negative dim likewise.
    good[d0:d0 + 8] = np.int64(-1).tobytes()
    with pytest.raises(ValueError):
        sw.decode_frame(bytes(good))
