"""Wire-frame codec unit tests (csrc/vcsnap.cc vcsnap_frame_* +
cache/snapwire.py): roundtrip fidelity, native/numpy layout parity,
hostile-input rejection."""

import numpy as np
import pytest

from volcano_tpu.cache import snapwire as sw


def _cases():
    rng = np.random.RandomState(7)
    return [
        np.arange(12, dtype=np.float32).reshape(3, 4),
        np.array([], np.int16),
        rng.randint(0, 255, (5, 2, 3)).astype(np.uint8),
        np.array(True),  # 0-dim
        rng.standard_normal((7,)).astype(np.float64),
        np.array([[1, -2], [3, 4]], np.int64),
        np.zeros((2, 0, 3), np.int32),  # zero-size middle dim
    ]


def test_roundtrip_native_or_fallback():
    arrays = _cases()
    man = {"op": "solve", "k": [1, 2.5, "x"], "wave": None}
    buf = sw.encode_frame(arrays, man)
    m2, arrs2 = sw.decode_frame(buf)
    assert m2 == man
    for a, b in zip(arrays, arrs2):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert np.array_equal(a, b)


def test_fallback_layout_byte_identical(monkeypatch):
    arrays = _cases()
    man = {"m": "x"}
    native = sw.encode_frame(arrays, man)
    monkeypatch.setattr(sw, "lib_or_none", lambda: None)
    fallback = sw.encode_frame(arrays, man)
    assert native == fallback
    m, arrs = sw.decode_frame(native)  # fallback parser reads native frame
    assert m == man and all(
        np.array_equal(a, b) for a, b in zip(arrays, arrs)
    )


@pytest.mark.parametrize("use_native", [True, False])
def test_malformed_frames_rejected(monkeypatch, use_native):
    if not use_native:
        monkeypatch.setattr(sw, "lib_or_none", lambda: None)
    good = sw.encode_frame([np.arange(4, dtype=np.int32)], {})
    with pytest.raises(ValueError):
        sw.decode_frame(b"nope")
    with pytest.raises(ValueError):
        sw.decode_frame(good[:20])  # truncated mid-headers
    bad_magic = b"XXXX" + good[4:]
    with pytest.raises(ValueError):
        sw.decode_frame(bad_magic)


def test_tree_flatten_roundtrip():
    from volcano_tpu.ops.allocate import SolveJobs

    arrays: list = []
    tree = sw.flatten_tree(
        (SolveJobs(queue=np.zeros(3, np.int32),
                   min_available=np.ones(3, np.int32),
                   ready_base=np.zeros(3, np.int32)),
         None, 2.5, "s", (np.array([1.0], np.float32),)),
        arrays,
    )
    out = sw.unflatten_tree(tree, arrays, {"SolveJobs": SolveJobs})
    jobs, none_v, f, s, tup = out
    assert isinstance(jobs, SolveJobs) and none_v is None
    assert f == 2.5 and s == "s"
    assert np.array_equal(tup[0], [1.0])


@pytest.mark.parametrize("use_native", [True, False])
def test_hostile_count_and_dtype_rejected(monkeypatch, use_native):
    """A corrupt header must not size allocations (huge array count) or
    index dtype tables (out-of-range code) — both parsers reject with
    ValueError before touching memory."""
    if not use_native:
        monkeypatch.setattr(sw, "lib_or_none", lambda: None)
    # magic+version intact, n_arrays = 0x7FFFFFFF, no manifest
    evil = np.array([0x4E534356, 1, 0x7FFFFFFF, 0], np.uint32).tobytes()
    with pytest.raises(ValueError):
        sw.decode_frame(evil)
    good = bytearray(sw.encode_frame([np.arange(4, dtype=np.int32)], {}))
    good[16] = 200  # dtype code out of range
    with pytest.raises(ValueError):
        sw.decode_frame(bytes(good))


@pytest.mark.parametrize("use_native", [True, False])
def test_dims_nbytes_mismatch_rejected(monkeypatch, use_native):
    """A corrupt dim that disagrees with the recorded byte length must
    not decode into a view bleeding into the next array's bytes."""
    if not use_native:
        monkeypatch.setattr(sw, "lib_or_none", lambda: None)
    good = bytearray(sw.encode_frame(
        [np.arange(8, dtype=np.int32).reshape(2, 4),
         np.arange(6, dtype=np.int32)], {}))
    # First array header starts after the 16-byte frame header plus the
    # manifest ("{}" = 2 bytes) padded to 8; dims are at +8 within it.
    # Double dim0 from 2 to 4.
    man_len = len(b"{}")
    d0 = ((16 + man_len + 7) & ~7) + 8
    dim0 = np.frombuffer(bytes(good[d0:d0 + 8]), np.int64)[0]
    assert dim0 == 2
    good[d0:d0 + 8] = np.int64(4).tobytes()
    with pytest.raises(ValueError):
        sw.decode_frame(bytes(good))
    # Negative dim likewise.
    good[d0:d0 + 8] = np.int64(-1).tobytes()
    with pytest.raises(ValueError):
        sw.decode_frame(bytes(good))


# ------------------------------------------------ delta records (ISSUE 10)


def test_diff_rows_bitwise_identity():
    """Row diffing is BIT identity: -0.0 vs 0.0 and NaN-payload changes
    must register as changed rows (they alter wire bytes), while
    bit-identical NaNs must not."""
    old = np.zeros((6, 2), np.float64)
    old[3, 0] = np.nan
    new = old.copy()
    assert len(sw.diff_rows(new, old)) == 0  # NaN == NaN bitwise
    new[0, 1] = -0.0  # compares == 0.0 but differs bitwise
    r = sw.diff_rows(new, old)
    assert r.tolist() == [[0, 1]]
    # Adjacent + separate changes coalesce into ascending ranges.
    new[1, 0] = 7.0
    new[5, 1] = 8.0
    assert sw.diff_rows(new, old).tolist() == [[0, 2], [5, 6]]
    # Shape/dtype drift is not row-diffable: the slot ships whole.
    assert sw.diff_rows(new, old.astype(np.float32)) is None
    assert sw.diff_rows(new[:5], old) is None


@pytest.mark.parametrize("use_native", [True, False])
def test_delta_check_native_numpy_parity(monkeypatch, use_native):
    """The python fallback and the C++ validator agree verdict-for-
    verdict on valid and hostile descriptors (same contract the csrc
    ASAN smoke pins natively)."""
    if not use_native:
        monkeypatch.setattr(sw, "lib_or_none", lambda: None)
    rows, row_bytes = 8, 4
    ok = np.array([2, 1, 3, 5, 6], np.int64)
    assert sw.delta_check(ok, rows, row_bytes, 12, 7, 7) == 3
    # Base-generation mismatch -> -2 (fall back to a full frame).
    assert sw.delta_check(ok, rows, row_bytes, 12, 7, 6) == -2
    # Truncated descriptor / hostile count near INT64_MAX.
    assert sw.delta_check(np.array([2, 1, 3], np.int64),
                          rows, row_bytes, 12, 7, 7) == -1
    huge = np.array([np.iinfo(np.int64).max - 1, 1, 3], np.int64)
    assert sw.delta_check(huge, rows, row_bytes, 12, 7, 7) == -1
    # Payload length mismatch / non-integral rows.
    assert sw.delta_check(ok, rows, row_bytes, 8, 7, 7) == -1
    assert sw.delta_check(ok, rows, row_bytes, 11, 7, 7) == -1
    # Overlapping, unsorted, empty, negative and out-of-bounds ranges.
    for bad in ([2, 1, 4, 3, 6], [2, 5, 6, 1, 3], [1, 2, 2],
                [1, -1, 2], [1, 0, np.iinfo(np.int64).max - 2]):
        n_rows = sum(max(0, int(bad[i + 2]) - int(bad[i + 1]))
                     for i in range(0, 2 * int(bad[0]), 2)
                     ) if bad[0] < 4 else 0
        assert sw.delta_check(np.array(bad, np.int64), rows, row_bytes,
                              n_rows * row_bytes, 7, 7) == -1
    # Non-int64 / non-1d descriptors are rejected before either engine.
    assert sw.delta_check(np.array([0], np.int32), rows, row_bytes,
                          0, 7, 7) == -1
    # Empty delta ("nothing changed") is valid.
    assert sw.delta_check(np.array([0], np.int64), rows, row_bytes,
                          0, 7, 7) == 0


@pytest.mark.parametrize("use_native", [True, False])
def test_delta_roundtrip_scatter(monkeypatch, use_native):
    """diff_rows -> ranges_to_desc/gather_rows -> delta_apply recreates
    the new array exactly, through both engines, and a rejected delta
    leaves the mirror untouched."""
    if not use_native:
        monkeypatch.setattr(sw, "lib_or_none", lambda: None)
    rng = np.random.RandomState(3)
    for dtype, cols in ((np.float32, 5), (np.int64, 3), (np.uint8, 17)):
        old = rng.randint(0, 200, (64, cols)).astype(dtype)
        new = old.copy()
        for row in (0, 1, 13, 14, 15, 63):
            new[row] = rng.randint(0, 200, cols).astype(dtype)
        r = sw.diff_rows(new, old)
        assert len(r) >= 1
        desc = sw.ranges_to_desc(r)
        payload = sw.gather_rows(new, r)
        mirror = old.copy()
        sw.delta_apply(mirror, desc, payload, 5, 5)
        assert np.array_equal(
            mirror.view(np.uint8), new.view(np.uint8))
        # Wrong base generation: ValueError, mirror untouched.
        mirror2 = old.copy()
        with pytest.raises(ValueError):
            sw.delta_apply(mirror2, desc, payload, 5, 4)
        assert np.array_equal(mirror2, old)
        # Malformed descriptor: ValueError, mirror untouched.
        bad = desc.copy()
        bad[0] = np.iinfo(np.int64).max - 1
        with pytest.raises(ValueError):
            sw.delta_apply(mirror2, bad, payload, 5, 5)
        assert np.array_equal(mirror2, old)


def test_encode_frame_views_byte_identical():
    """The scatter-gather encode produces the EXACT byte stream of
    encode_frame — total length and concatenated buffers — without
    copying any array payload (the data parts are memoryviews into the
    caller's arrays)."""
    arrays = _cases()
    man = {"op": "solve", "wire": {"gen": 3}, "wave": None}
    ref = sw.encode_frame(arrays, man)
    total, parts = sw.encode_frame_views(arrays, man)
    assert total == len(ref)
    assert b"".join(bytes(p) for p in parts) == ref
    # The payload parts alias the source arrays (zero-copy proof): a
    # contiguous input's memoryview shares its buffer.
    a = np.arange(32, dtype=np.int64)
    _, pv = sw.encode_frame_views([a], {})
    views = [p for p in pv if isinstance(p, memoryview)]
    assert len(views) == 1
    a[0] = 99  # mutating the array is visible through the view
    assert bytes(views[0][:8]) == np.int64(99).tobytes()
