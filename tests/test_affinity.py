"""Inter-pod (anti)affinity and topology-spread device kernels.

Covers the per-(term, domain) count machinery (arrays/affinity.py + the
dynamic checks inside ops/allocate.solve) against the reference semantics
(predicates.go:272-291 via the upstream inter-pod predicate, including the
self-match rule) and the host predicate fallback, plus solver/oracle parity
on affinity-bearing random clusters.
"""

import numpy as np
import pytest

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    AffinityTerm,
    Node,
    Pod,
    PodGroup,
    PodPhase,
    TaskStatus,
)
from volcano_tpu.cache import ClusterStore
from volcano_tpu.oracle import solve_oracle
from volcano_tpu.ops.allocate import solve
from volcano_tpu.synth import solve_args_from_store

ZONES = ["zone-a", "zone-b", "zone-c"]
HOSTNAME = "kubernetes.io/hostname"


def _store_with_zones(n_per_zone=2, cpu="16", mem="64Gi"):
    store = ClusterStore()
    for z, zone in enumerate(ZONES):
        for i in range(n_per_zone):
            store.add_node(
                Node(
                    name=f"{zone}-n{i}",
                    allocatable={"cpu": cpu, "memory": mem, "pods": 32},
                    labels={"zone": zone},
                )
            )
    return store


def _gang(store, name, pods, min_member=None):
    pg = PodGroup(name=name, min_member=min_member or len(pods),
                  queue="default")
    store.add_pod_group(pg)
    for pod in pods:
        pod.annotations = dict(pod.annotations or {})
        pod.annotations[GROUP_NAME_ANNOTATION] = name
        store.add_pod(pod)
    return pg


def _solve_names(store):
    args, maps = solve_args_from_store(store)
    res = solve(*args)
    out = {}
    for i, ti in enumerate(maps.task_infos):
        n = int(np.asarray(res.assigned)[i])
        out[ti.name] = maps.node_names[n] if n >= 0 else None
    return out, res, args, maps


def test_affinity_pulls_gang_to_one_zone():
    store = _store_with_zones()
    term = AffinityTerm(match_labels={"app": "db"}, topology_key="zone")
    pods = [
        Pod(name=f"db-{k}", labels={"app": "db"},
            containers=[{"cpu": "2", "memory": "4Gi"}],
            affinity=[term])
        for k in range(4)
    ]
    _gang(store, "db", pods)
    names, res, _, _ = _solve_names(store)
    zones = {n.rsplit("-n", 1)[0] for n in names.values()}
    assert None not in names.values()
    assert len(zones) == 1, f"gang split across zones: {names}"


def test_anti_affinity_spreads_across_hosts():
    store = _store_with_zones(n_per_zone=2)  # 6 nodes
    term = AffinityTerm(match_labels={"app": "web"}, topology_key=HOSTNAME)
    pods = [
        Pod(name=f"web-{k}", labels={"app": "web"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
            anti_affinity=[term])
        for k in range(6)
    ]
    _gang(store, "web", pods)
    names, _, _, _ = _solve_names(store)
    assert None not in names.values()
    assert len(set(names.values())) == 6, f"anti-affinity violated: {names}"


def test_anti_affinity_infeasible_when_hosts_exhausted():
    store = _store_with_zones(n_per_zone=1)  # 3 nodes
    term = AffinityTerm(match_labels={"app": "web"}, topology_key=HOSTNAME)
    pods = [
        Pod(name=f"web-{k}", labels={"app": "web"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
            anti_affinity=[term])
        for k in range(4)
    ]
    _gang(store, "web", pods, min_member=4)
    names, res, _, _ = _solve_names(store)
    # Gang needs 4 distinct hosts but only 3 exist: all-or-nothing discard.
    assert all(v is None for v in names.values())
    assert bool(np.asarray(res.fit_failed)[0])


def test_affinity_to_resident_pod():
    store = _store_with_zones()
    store.add_pod(
        Pod(name="existing-db", labels={"app": "db"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
            phase=PodPhase.Running, node_name="zone-b-n0")
    )
    term = AffinityTerm(match_labels={"app": "db"}, topology_key="zone")
    pods = [
        Pod(name="client-0", labels={"app": "client"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
            affinity=[term])
    ]
    _gang(store, "client", pods)
    names, _, _, _ = _solve_names(store)
    assert names["client-0"] in ("zone-b-n0", "zone-b-n1")


def test_anti_affinity_against_resident_pod():
    store = _store_with_zones(n_per_zone=1)
    store.add_pod(
        Pod(name="existing", labels={"app": "solo"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
            phase=PodPhase.Running, node_name="zone-a-n0")
    )
    term = AffinityTerm(match_labels={"app": "solo"}, topology_key="zone")
    pods = [
        Pod(name="new-0", labels={"app": "solo"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
            anti_affinity=[term])
    ]
    _gang(store, "solo", pods)
    names, _, _, _ = _solve_names(store)
    assert names["new-0"] is not None
    assert not names["new-0"].startswith("zone-a")


def test_self_match_rule_allows_first_pod():
    """A self-affine gang (every pod requires affinity to its own label)
    must still schedule: the first pod passes via the self-match rule and
    the dynamic counts pull the rest into its domain."""
    store = _store_with_zones()
    term = AffinityTerm(match_labels={"app": "ring"}, topology_key="zone")
    pods = [
        Pod(name=f"ring-{k}", labels={"app": "ring"},
            containers=[{"cpu": "2", "memory": "4Gi"}],
            affinity=[term])
        for k in range(3)
    ]
    _gang(store, "ring", pods)
    names, _, _, _ = _solve_names(store)
    assert None not in names.values()
    zones = {n.rsplit("-n", 1)[0] for n in names.values()}
    assert len(zones) == 1


def test_topology_spread_soft():
    """Soft spread pushes gang mates into distinct zones when capacity
    allows (no hard constraint)."""
    store = _store_with_zones(n_per_zone=1)
    pods = [
        Pod(name=f"spread-{k}", labels={"app": "spread"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
            topology_spread=[("zone", 1000)])
        for k in range(3)
    ]
    _gang(store, "spread", pods)
    names, _, _, _ = _solve_names(store)
    assert None not in names.values()
    assert len(set(names.values())) == 3, f"spread failed: {names}"


def test_preferred_affinity_colocates():
    store = _store_with_zones()
    store.add_pod(
        Pod(name="cache", labels={"app": "cache"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
            phase=PodPhase.Running, node_name="zone-c-n1")
    )
    term = AffinityTerm(match_labels={"app": "cache"}, topology_key="zone")
    pods = [
        Pod(name="worker-0", labels={"app": "worker"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
            preferred_affinity=[(term, 1000)])
    ]
    _gang(store, "worker", pods)
    names, _, _, _ = _solve_names(store)
    assert names["worker-0"].startswith("zone-c")


def test_device_matches_host_predicate_static():
    """For the first pending task (no intra-cycle placements yet), the
    device feasibility of affinity terms must agree with the host
    predicate_fn on every node."""
    from volcano_tpu.framework import parse_scheduler_conf
    from volcano_tpu.framework.framework import close_session, open_session
    from volcano_tpu.scheduler import DEFAULT_SCHEDULER_CONF

    store = _store_with_zones()
    store.add_pod(
        Pod(name="resident-db", labels={"app": "db"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
            phase=PodPhase.Running, node_name="zone-a-n0")
    )
    aff_term = AffinityTerm(match_labels={"app": "db"}, topology_key="zone")
    anti_term = AffinityTerm(match_labels={"app": "db"}, topology_key=HOSTNAME)
    pods = [
        Pod(name="aff-pod", labels={"app": "x"},
            containers=[{"cpu": "1", "memory": "1Gi"}], affinity=[aff_term]),
        Pod(name="anti-pod", labels={"app": "y"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
            anti_affinity=[anti_term]),
    ]
    _gang(store, "mixed", pods, min_member=1)

    conf = parse_scheduler_conf(DEFAULT_SCHEDULER_CONF)
    ssn = open_session(store, conf.tiers, conf.configurations)
    try:
        snap_nodes = ssn.nodes
        for task in [
            t for j in ssn.jobs.values()
            for t in j.task_status_index.get(TaskStatus.Pending, {}).values()
        ]:
            host_ok = {}
            for name, node in snap_nodes.items():
                try:
                    ssn.predicate_fn(task, node)
                    host_ok[name] = True
                except Exception:
                    host_ok[name] = False
            # Device: encode this task alone and read its feasible row via
            # a 1-task solve on an infinite-min gang (no commit effects).
            args, maps = solve_args_from_store(store)
            res = solve(*args)
            i = maps.task_uids.index(task.uid)
            n = int(np.asarray(res.assigned)[i])
            if n >= 0:
                assert host_ok[maps.node_names[n]], (
                    f"device placed {task.name} on a host-rejected node"
                )
    finally:
        close_session(ssn)


@pytest.mark.parametrize("seed", range(8))
def test_oracle_parity_with_affinity(seed):
    rng = np.random.default_rng(1000 + seed)
    store = _store_with_zones(n_per_zone=int(rng.integers(1, 4)))
    n_gangs = int(rng.integers(2, 7))
    for g in range(n_gangs):
        size = int(rng.integers(1, 5))
        kind = rng.integers(0, 5)
        pods = []
        for k in range(size):
            pod = Pod(
                name=f"g{g}-p{k}",
                labels={"app": f"app-{g}"},
                containers=[{
                    "cpu": str(int(rng.integers(1, 5))),
                    "memory": f"{int(rng.integers(1, 9))}Gi",
                }],
            )
            term = AffinityTerm(
                match_labels={"app": f"app-{g}"},
                topology_key="zone" if rng.random() < 0.5 else HOSTNAME,
            )
            if kind == 0:
                pod.affinity = [term]
            elif kind == 1:
                pod.anti_affinity = [term]
            elif kind == 2:
                pod.topology_spread = [("zone", 100)]
            elif kind == 3:
                pod.preferred_affinity = [(term, 50)]
            pods.append(pod)
        _gang(store, f"g{g}", pods, min_member=int(rng.integers(1, size + 1)))

    args, _ = solve_args_from_store(store)
    got = solve(*args)
    want = solve_oracle(*args)
    np.testing.assert_array_equal(np.asarray(got.assigned), want.assigned)
    np.testing.assert_array_equal(np.asarray(got.pipelined), want.pipelined)
    np.testing.assert_array_equal(np.asarray(got.never_ready), want.never_ready)
    np.testing.assert_array_equal(np.asarray(got.fit_failed), want.fit_failed)


def test_same_domain_affinity_siblings_place_in_few_subrounds():
    """Required-affinity siblings landing in the earlier sibling's domain
    are mutually consistent and must place together, not one per
    sub-round: a 12-task self-affinity gang on a 2-zone cluster should
    resolve in a handful of solver iterations, not O(gang size)."""
    store = _store_with_zones(n_per_zone=4, cpu="16")
    term = AffinityTerm(match_labels={"app": "big"}, topology_key="zone")
    pods = [
        Pod(name=f"big-{k}", labels={"app": "big"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
            affinity=[term])
        for k in range(12)
    ]
    _gang(store, "big", pods)
    from volcano_tpu.ops.wave import solve_wave
    from volcano_tpu.synth import solve_args_from_store

    args, maps = solve_args_from_store(store)
    res = solve_wave(*args)
    names = {}
    for i, ti in enumerate(maps.task_infos):
        n = int(np.asarray(res.assigned)[i])
        names[ti.name] = maps.node_names[n] if n >= 0 else None
    assert None not in names.values()
    zones = {n.rsplit("-n", 1)[0] for n in names.values()}
    assert len(zones) == 1, f"gang split across zones: {names}"
    iters = int(np.asarray(res.iters))
    assert iters <= 8, (
        f"same-domain affinity siblings serialized: {iters} iterations "
        f"for a 12-task gang"
    )
