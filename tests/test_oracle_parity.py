"""Solver-vs-oracle parity: the CPU-reference harness of SURVEY.md M5.

The JAX solver (``ops/allocate.solve``) and the NumPy Go-semantics oracle
(``volcano_tpu/oracle.py``) consume the same dense snapshot; on every
randomized cluster they must produce identical assignment matrices.  Also
checks the invariants the reference enforces structurally: gang atomicity
(all-or-nothing vs min_available) and resource conservation (no node gives
out more than the assigned tasks' requests).
"""

import numpy as np
import pytest

from volcano_tpu.api import GROUP_NAME_ANNOTATION, Node, Pod, PodGroup, Queue, Taint, Toleration
from volcano_tpu.cache import ClusterStore
from volcano_tpu.oracle import solve_oracle
from volcano_tpu.ops.allocate import solve
from volcano_tpu.synth import solve_args_from_store, synthetic_cluster


def _random_store(seed: int) -> ClusterStore:
    """A messy randomized cluster: heterogeneous nodes, labels, taints,
    host ports, selectors, gangs of varied min_member, several queues."""
    rng = np.random.default_rng(seed)
    store = ClusterStore()
    n_nodes = int(rng.integers(4, 24))
    zones = ["zone-a", "zone-b", "zone-c"]
    for i in range(n_nodes):
        labels = {"zone": zones[i % len(zones)]}
        if rng.random() < 0.3:
            labels["disk"] = "ssd"
        taints = []
        if rng.random() < 0.25:
            taints.append(Taint(key="dedicated", value="batch", effect="NoSchedule"))
        store.add_node(
            Node(
                name=f"node-{i:03d}",
                allocatable={
                    "cpu": str(int(rng.integers(4, 33))),
                    "memory": f"{int(rng.integers(8, 65))}Gi",
                    "pods": int(rng.integers(4, 64)),
                },
                labels=labels,
                taints=taints,
            )
        )
    for q in range(1, int(rng.integers(1, 4))):
        store.add_queue(Queue(name=f"queue-{q}", weight=int(rng.integers(1, 5))))
    queues = ["default"] + [q for q in store.snapshot().queues if q != "default"]

    n_gangs = int(rng.integers(2, 14))
    for g in range(n_gangs):
        size = int(rng.integers(1, 6))
        min_member = int(rng.integers(1, size + 1))
        pg = PodGroup(
            name=f"pg-{g:03d}",
            min_member=min_member,
            queue=str(rng.choice(queues)),
        )
        store.add_pod_group(pg)
        for k in range(size):
            selector = {}
            if rng.random() < 0.3:
                selector["zone"] = str(rng.choice(zones))
            tolerations = []
            if rng.random() < 0.4:
                tolerations.append(
                    Toleration(key="dedicated", operator="Equal",
                               value="batch", effect="NoSchedule")
                )
            ports = []
            if rng.random() < 0.25:
                ports.append(int(rng.choice([8080, 9090, 9100])))
            store.add_pod(
                Pod(
                    name=f"pg-{g:03d}-{k}",
                    annotations={GROUP_NAME_ANNOTATION: pg.name},
                    containers=[{
                        "cpu": str(int(rng.integers(1, 9))),
                        "memory": f"{int(rng.integers(1, 17))}Gi",
                    }],
                    node_selector=selector,
                    tolerations=tolerations,
                    host_ports=ports,
                    priority=int(rng.integers(0, 3)),
                )
            )
    return store


def _compare(args):
    got = solve(*args)
    want = solve_oracle(*args)
    np.testing.assert_array_equal(np.asarray(got.assigned), want.assigned)
    np.testing.assert_array_equal(np.asarray(got.pipelined), want.pipelined)
    np.testing.assert_array_equal(np.asarray(got.never_ready), want.never_ready)
    np.testing.assert_array_equal(np.asarray(got.fit_failed), want.fit_failed)
    np.testing.assert_allclose(
        np.asarray(got.idle), want.idle, rtol=1e-5, atol=1e-2
    )
    np.testing.assert_allclose(
        np.asarray(got.q_alloc), want.q_alloc, rtol=1e-5, atol=1e-2
    )
    return got, want


@pytest.mark.parametrize("seed", range(12))
def test_parity_random_clusters(seed):
    args, _ = solve_args_from_store(_random_store(seed))
    _compare(args)


@pytest.mark.parametrize("seed", [0, 1])
def test_parity_synthetic_uniform(seed):
    store = synthetic_cluster(n_nodes=32, n_pods=96, gang_size=3,
                              n_queues=2, seed=seed)
    args, _ = solve_args_from_store(store)
    _compare(args)


def test_parity_oversubscribed_gangs():
    """Cluster too small for all gangs: discard paths must agree."""
    store = synthetic_cluster(
        n_nodes=4, n_pods=64, gang_size=8,
        pod_cpu_choices=("8", "16"), pod_mem_choices=("16Gi", "32Gi"),
    )
    args, _ = solve_args_from_store(store)
    got, want = _compare(args)
    assert np.asarray(got.never_ready).any() or np.asarray(got.fit_failed).any()


@pytest.mark.parametrize("seed", range(6))
def test_invariants(seed):
    args, maps = solve_args_from_store(_random_store(seed))
    res = solve(*args)
    assigned = np.asarray(res.assigned)
    idle_final = np.asarray(res.idle)
    s_nodes, s_tasks, s_jobs = args[0], args[1], args[2]
    idle0 = np.asarray(s_nodes.idle)
    req = np.asarray(s_tasks.req)
    task_job = np.asarray(s_tasks.job)
    task_real = np.asarray(s_tasks.real)
    min_available = np.asarray(s_jobs.min_available)
    ready_base = np.asarray(s_jobs.ready_base)

    # Resource conservation: node idle decreases exactly by the sum of
    # committed requests.
    expect = idle0.copy()
    for t, n in enumerate(assigned):
        if n >= 0:
            expect[n] -= req[t]
    np.testing.assert_allclose(idle_final, expect, rtol=1e-5, atol=1e-2)

    # Gang atomicity: a job either reaches min_available or commits nothing.
    J = min_available.shape[0]
    counts = np.zeros((J,), int)
    for t, n in enumerate(assigned):
        if n >= 0 and task_real[t]:
            counts[task_job[t]] += 1
    for j in range(J):
        if counts[j] > 0:
            assert counts[j] + ready_base[j] >= min_available[j], (
                f"job {j}: committed {counts[j]} < min {min_available[j]}"
            )

    # No node oversubscription beyond the epsilon quantum per task.
    assert (idle_final >= -1e-2 * max(1, len(assigned))).all()
