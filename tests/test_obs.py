"""Observability layer (ISSUE 3): trace spans, the cycle flight
recorder, Perfetto export, and the /debug endpoints.

Pins the acceptance contracts:

- a pipelined run's exported trace contains dispatch and commit spans
  for the SAME solve-id in adjacent cycles, linked via flow references,
  and loads cleanly as Chrome ``trace_event`` JSON;
- forced staleness drops (concurrent delete + competing bind + node
  churn) produce per-reason drop counters that sum exactly to the
  dropped rows, with ``/debug/cycles`` returning the matching record;
- the ring buffer is bounded; lane breakdowns survive tracing being
  disabled (bench compatibility).

All CPU-only (conftest pins JAX_PLATFORMS=cpu); tier-1.
"""

import copy
import json
import urllib.request

import pytest

from volcano_tpu.api import GROUP_NAME_ANNOTATION, Node, Pod, PodGroup
from volcano_tpu.cache import ClusterStore
from volcano_tpu.metrics import metrics
from volcano_tpu.obs import CycleRecord, FlightRecorder, export
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.synth import synthetic_cluster

pytestmark = pytest.mark.tier1


def _small(seed=7, **kw):
    kw.setdefault("n_nodes", 8)
    kw.setdefault("n_pods", 32)
    kw.setdefault("gang_size", 4)
    return synthetic_cluster(seed=seed, **kw)


# ------------------------------------------------------------ trace export


def test_pipelined_trace_links_dispatch_and_commit_across_cycles():
    """The acceptance contract: dispatch span (cycle N) and the
    fetch/commit spans (cycle N+1) share one solve-id flow, the export
    emits matching flow start/finish events, and the whole trace
    round-trips as JSON."""
    store = _small()
    store.pipeline = True
    sched = Scheduler(store)
    sched.run_once()  # cycle 1: dispatch only
    sched.run_once()  # cycle 2: commit lands
    store.flush_binds()

    recs = store.flight.recent()
    assert len(recs) == 2
    c1, c2 = recs
    solve_id = c1.dispatched_solve_id
    assert solve_id is not None
    # The SAME solve-id committed in the adjacent cycle.
    assert c2.committed_solve_id == solve_id
    dispatch_spans = [s for s in c1.spans if s.name == "dispatch"]
    commit_spans = [s for s in c2.spans
                    if s.name in ("inflight_fetch", "inflight_commit")]
    assert len(dispatch_spans) == 1
    assert len(commit_spans) == 2
    assert dispatch_spans[0].flow == solve_id
    assert all(s.flow == solve_id for s in commit_spans)

    # Export round-trips as Chrome trace_event JSON.
    blob = json.dumps(export.perfetto_trace(recs))
    trace = json.loads(blob)
    events = trace["traceEvents"]
    assert isinstance(events, list) and events
    for ev in events:
        assert "ph" in ev and "pid" in ev and "name" in ev
        if ev["ph"] != "M":
            assert isinstance(ev["ts"], (int, float))
    # Flow arrow: one start + one finish carrying the solve-id, start
    # on the dispatch, finish on the commit side, in time order.
    starts = [ev for ev in events
              if ev["ph"] == "s" and ev["id"] == solve_id]
    finishes = [ev for ev in events
                if ev["ph"] == "f" and ev["id"] == solve_id]
    assert len(starts) == 1 and len(finishes) == 1
    assert starts[0]["ts"] < finishes[0]["ts"]
    # Complete events for the linked spans exist with the flow id in
    # their args.
    xnames = {ev["name"] for ev in events if ev["ph"] == "X"}
    assert {"dispatch", "inflight_fetch", "inflight_commit"} <= xnames


def test_cycle_record_fields_cover_overlap_accounting():
    store = _small(seed=11)
    store.pipeline = True
    sched = Scheduler(store)
    sched.run_once()
    sched.run_once()
    store.flush_binds()
    recs = store.flight.recent()
    # Cycle 1 considered all 32 pending rows exactly once (no
    # double-counting across solver rounds).
    assert recs[0].pods_considered == 32
    rec = recs[-1]
    assert rec.path == "fast"
    assert rec.pods_bound == 32
    assert rec.inflight_fetch_wait_ms is not None
    # Nothing moved during the overlap: dispatch and commit see the
    # same mirror state.
    assert rec.mutation_seq_at_dispatch == rec.mutation_seq_at_commit
    assert rec.epoch_at_dispatch == rec.epoch_at_commit
    assert rec.duration_s > 0
    d = rec.to_dict()
    assert d["lanes_ms"] and "derive" in d["lanes_ms"]
    json.dumps(d)  # JSON-serializable as served by /debug/cycles


# ------------------------------------------------------ staleness reasons


def _drop_scenario_store():
    """Two roomy nodes, five plain pods, one selector pod — every
    staleness-drop reason below is then forceable during the overlap."""
    store = ClusterStore()
    store.add_node(Node(
        name="n0", allocatable={"cpu": "8", "memory": "32Gi", "pods": 64},
        labels={"zone": "a"},
    ))
    store.add_node(Node(
        name="n1", allocatable={"cpu": "8", "memory": "32Gi", "pods": 64},
    ))
    pg = PodGroup(name="g", min_member=1)
    store.add_pod_group(pg)
    for k in range(5):
        store.add_pod(Pod(
            name=f"p{k}",
            annotations={GROUP_NAME_ANNOTATION: pg.name},
            containers=[{"cpu": "1", "memory": "1Gi"}],
        ))
    store.add_pod(Pod(
        name="picky",
        annotations={GROUP_NAME_ANNOTATION: pg.name},
        containers=[{"cpu": "1", "memory": "1Gi"}],
        node_selector={"zone": "a"},
    ))
    store.pipeline = True
    return store


def _counter_totals():
    return dict(metrics.pipeline_stale_drops.data)


def test_drop_reasons_sum_exactly_to_dropped_rows():
    """Concurrent delete + competing bind + node churn during the
    overlap: the per-reason counts sum exactly to the dropped rows, and
    each forced reason is attributed."""
    store = _drop_scenario_store()
    sched = Scheduler(store)
    sched.run_once()  # dispatch over the 6 pending pods
    assert store._inflight_solve is not None

    # deleted: p0 goes away.
    victim = next(p for p in store.pods.values() if p.name == "p0")
    store.delete_pod(victim)
    # competing-bind: p1 is bound by "someone else" mid-overlap.
    p1 = next(p for p in store.pods.values() if p.name == "p1")
    p1b = copy.copy(p1)
    p1b.node_name = "n1"
    store.update_pod(p1b)
    # node-epoch-churn: the node table moves, so the selector row
    # ("picky") solved against stale label planes.
    store.add_node(Node(
        name="n1", allocatable={"cpu": "8", "memory": "32Gi", "pods": 64},
        labels={"freshly": "labelled"},
    ))

    before = _counter_totals()
    sched.run_once()  # fetch + staleness-guarded commit
    store.flush_binds()

    rec = next(r for r in reversed(store.flight.recent())
               if r.committed_solve_id is not None)
    assert rec.pods_dropped > 0
    assert sum(rec.drop_reasons.values()) == rec.pods_dropped
    assert rec.drop_reasons.get("deleted") == 1
    assert rec.drop_reasons.get("competing-bind") == 1
    # Node churn drops every node-sensitive row; "picky" is one of them.
    assert rec.drop_reasons.get("node-epoch-churn", 0) >= 1
    # The counter series moved by exactly the recorded amounts.
    after = _counter_totals()
    for reason, n in rec.drop_reasons.items():
        key = (("reason", reason),)
        assert after.get(key, 0.0) - before.get(key, 0.0) == n


def test_capacity_theft_attributed_as_capacity_taken():
    store = ClusterStore()
    for i in range(2):
        store.add_node(Node(
            name=f"n{i}",
            allocatable={"cpu": "1", "memory": "8Gi", "pods": 64},
        ))
    store.add_pod_group(PodGroup(name="g", min_member=1))
    for k in range(2):
        store.add_pod(Pod(
            name=f"p{k}",
            annotations={GROUP_NAME_ANNOTATION: "g"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
        ))
    store.pipeline = True
    sched = Scheduler(store)
    sched.run_once()  # dispatch: p0 -> one node, p1 -> the other
    for i in range(2):
        store.add_pod(Pod(
            name=f"thief{i}",
            annotations={GROUP_NAME_ANNOTATION: "g"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
            node_name=f"n{i}",
        ))
    sched.run_once()  # guard drops both rows
    rec = next(r for r in reversed(store.flight.recent())
               if r.committed_solve_id is not None)
    assert rec.drop_reasons == {"capacity-taken": 2}
    assert rec.pods_dropped == 2


def test_lost_reply_recorded_not_as_clean_commit(monkeypatch):
    """A remote solve whose reply is lost must NOT record a committed
    solve-id with zero drops (that reads as a clean commit); the rows
    count under the lost-reply reason and the event names the solve."""
    from volcano_tpu import pipeline as pl

    store = _small(seed=19)
    store.pipeline = True
    sched = Scheduler(store)
    sched.run_once()
    inflight = store._inflight_solve
    assert inflight is not None
    n_rows = len(inflight.task_rows)
    inflight.kind = "remote"  # present the handle as a remote dispatch

    def lost(self):
        raise OSError("connection reset by peer")

    monkeypatch.setattr(pl.InflightSolve, "fetch", lost)
    sched.run_once()
    rec = store.flight.recent()[-1]
    assert rec.committed_solve_id is None
    assert rec.drop_reasons.get("lost-reply") == n_rows
    assert any("reply lost" in ev for ev in rec.device_events)


def test_compaction_void_counts_whole_result():
    store = _small(seed=9)
    store.pipeline = True
    sched = Scheduler(store)
    sched.run_once()
    n_inflight = len(store._inflight_solve.task_rows)
    store.mirror.compact_gen += 1  # what maybe_compact() does
    sched.run_once()
    rec = store.flight.recent()[-1]
    assert rec.drop_reasons.get("compaction") == n_inflight


# ------------------------------------------------------- /debug endpoints


def test_debug_endpoints_serve_ring_and_trace():
    """/debug/cycles, /debug/cycles/<seq> and /debug/trace serve the
    flight recorder over HTTP, including the drop accounting of a
    staleness-guarded cycle."""
    from volcano_tpu.service import Service

    store = _drop_scenario_store()
    sched = Scheduler(store)
    sched.run_once()
    victim = next(p for p in store.pods.values() if p.name == "p0")
    store.delete_pod(victim)
    sched.run_once()
    store.flush_binds()
    want = next(r for r in reversed(store.flight.recent())
                if r.committed_solve_id is not None)

    svc = Service(store=store, schedule_period=30.0,
                  controller_period=5.0)
    port = svc.start(http_port=0)
    try:
        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=10) as r:
                return json.loads(r.read())

        cycles = get("/debug/cycles")
        assert isinstance(cycles, list) and cycles
        match = [c for c in cycles if c["seq"] == want.seq]
        assert match, "the staleness cycle is in the served ring"
        assert match[0]["drop_reasons"] == dict(want.drop_reasons)
        assert match[0]["pods_dropped"] == want.pods_dropped
        assert (sum(match[0]["drop_reasons"].values())
                == match[0]["pods_dropped"])

        one = get(f"/debug/cycles/{want.seq}")
        assert one["seq"] == want.seq
        assert one["spans"], "per-cycle endpoint includes spans"

        trace = get("/debug/trace?cycles=8")
        assert "traceEvents" in trace and trace["traceEvents"]
        assert get("/debug/cycles?n=1")[-1]["seq"] == cycles[-1]["seq"]

        missing_rc = None
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/cycles/999999",
                timeout=10)
        except urllib.error.HTTPError as err:
            missing_rc = err.code
        assert missing_rc == 404
    finally:
        svc.stop()


# --------------------------------------------------------------- plumbing


def test_flight_recorder_ring_is_bounded():
    fr = FlightRecorder(capacity=4)
    for i in range(10):
        fr.record(CycleRecord(session=f"s{i}"))
    assert len(fr) == 4
    recs = fr.recent()
    assert [r.seq for r in recs] == [7, 8, 9, 10]
    assert fr.get(10).session == "s9"
    assert fr.get(1) is None
    assert fr.recent(2)[0].seq == 9
    assert fr.recent(0) == []
    assert fr.recent(-3) == []
    assert fr.last().seq == 10


def test_lanes_survive_tracing_disabled(monkeypatch):
    """VOLCANO_TPU_TRACE=0 drops span records but keeps the lane
    breakdown (bench.py compatibility)."""
    monkeypatch.setenv("VOLCANO_TPU_TRACE", "0")
    store = _small(seed=13)
    Scheduler(store).run_once()
    store.flush_binds()
    assert store.last_cycle_lanes
    assert "derive" in store.last_cycle_lanes
    rec = store.flight.recent()[-1]
    assert rec.spans == []
    assert rec.lanes


def test_object_session_cycles_are_recorded(monkeypatch):
    """The object path (fast path disabled) records cycles too, with
    snapshot/action/plugin spans."""
    monkeypatch.setenv("VOLCANO_TPU_FASTPATH", "0")
    store = _small(seed=17, n_nodes=4, n_pods=8, gang_size=2)
    Scheduler(store).run_once()
    store.flush_binds()
    rec = store.flight.recent()[-1]
    assert rec.path == "object"
    names = {s.name for s in rec.spans}
    assert "snapshot" in names
    assert any(n.startswith("action:") for n in names)
    assert any(n.startswith("plugin:") for n in names)
