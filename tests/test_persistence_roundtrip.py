"""Checkpoint/restore fidelity beyond the basics.

test_ha_persistence.py covers schedule-identical restores, claims,
policies, and leader election; these tests pin the remaining contract:
mid-flight batch jobs resume without duplicated side effects, commands
survive, saves are atomic under concurrent churn, and failure modes
(version mismatch, corrupt file) are loud.
"""

import pickle
import threading

import pytest

from volcano_tpu.api import GROUP_NAME_ANNOTATION, Node, Pod, PodGroup, PodGroupPhase
from volcano_tpu.cache import ClusterStore
from volcano_tpu.controllers import ControllerManager, Job, TaskSpec
from volcano_tpu.controllers.apis import Command, VolumeSpec
from volcano_tpu.persistence import FORMAT_VERSION, load_store, save_store
from volcano_tpu.scheduler import Scheduler


def running_job_store():
    """A job initiated, admitted, with pods created and bound — the
    mid-flight state a restart must resume from."""
    store = ClusterStore()
    store.add_node(Node(name="n0", allocatable={"cpu": "16",
                                                "memory": "32Gi",
                                                "pods": 110}))
    cm = ControllerManager(store)
    job = Job(name="j1", min_available=2,
              tasks=[TaskSpec(name="w", replicas=2,
                              containers=[{"cpu": "1", "memory": "1Gi"}])],
              volumes=[VolumeSpec(mount_path="/d",
                                  volume_claim={"storage": "1Gi"})])
    store.add_batch_job(job)
    cm.process()
    pg = store.pod_groups["default/j1"]
    pg.status.phase = PodGroupPhase.Inqueue.value
    store.update_pod_group(pg)
    store._notify("PodGroup", "status", pg)
    cm.process()
    Scheduler(store).run_once()
    return store, cm, job


def test_midflight_job_resumes_without_duplicate_side_effects(tmp_path):
    store, _cm, job = running_job_store()
    path = str(tmp_path / "ckpt.bin")
    save_store(store, path)
    restored = load_store(path)
    cm2 = ControllerManager(restored)
    job2 = restored.batch_jobs["default/j1"]
    # Status machinery state survived.
    assert job2.status.controlled_resources == job.status.controlled_resources
    assert job2.finalizers == job.finalizers
    n_pvcs = len(restored.pvcs)
    n_pods = len(restored.pods)
    # Reconciling the restored store is a no-op: no duplicate pods,
    # claims, or PodGroups (plugin markers + existing records gate it).
    cm2.process()
    cm2.process()
    assert len(restored.pvcs) == n_pvcs
    assert len(restored.pods) == n_pods
    assert list(restored.pod_groups) == ["default/j1"]
    # And scheduling the restored store reaches the same placements.
    Scheduler(restored).run_once()
    bound = {p.name: p.node_name for p in restored.pods.values()}
    orig = {p.name: p.node_name for p in store.pods.values()}
    assert bound == orig


def test_commands_survive_restart(tmp_path):
    store = ClusterStore()
    store.add_command(Command(action="AbortJob", target_kind="Job",
                              target_name="j9", name="pending-cmd"))
    path = str(tmp_path / "ckpt.bin")
    save_store(store, path)
    restored = load_store(path)
    assert "pending-cmd" in restored.commands
    assert restored.commands["pending-cmd"].action == "AbortJob"


def test_save_is_atomic_under_concurrent_churn(tmp_path):
    """Saves taken while another thread churns pods always load to a
    consistent snapshot (the payload is serialized under the store
    lock; the file write is tmp+rename)."""
    store = ClusterStore()
    store.add_node(Node(name="n0", allocatable={"cpu": "64",
                                                "memory": "128Gi",
                                                "pods": 256}))
    store.add_pod_group(PodGroup(name="g", min_member=1))
    stop = threading.Event()
    errors = []

    def churn():
        i = 0
        try:
            while not stop.is_set() and i < 500:
                i += 1
                pod = Pod(name=f"p-{i}",
                          annotations={GROUP_NAME_ANNOTATION: "g"},
                          containers=[{"cpu": "1", "memory": "1Gi"}])
                store.add_pod(pod)
                if i % 2 == 0:
                    store.delete_pod(pod)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    t = threading.Thread(target=churn)
    t.start()
    try:
        for k in range(10):
            path = str(tmp_path / f"ckpt-{k}.bin")
            save_store(store, path)
            restored = load_store(path)
            # Consistency: every restored pod round-trips through the
            # event API and lands in the mirror at its indexed row.
            for pod in restored.pods.values():
                row = restored.mirror.p_row[pod.uid]
                assert restored.mirror.p_pod[row] is pod
    finally:
        stop.set()
        t.join(timeout=10)
    assert not errors


def test_version_mismatch_raises(tmp_path):
    store = ClusterStore()
    path = str(tmp_path / "ckpt.bin")
    save_store(store, path)
    blob = pickle.load(open(path, "rb"))
    blob["version"] = FORMAT_VERSION + 999
    with open(path, "wb") as f:
        pickle.dump(blob, f)
    with pytest.raises(ValueError, match="unsupported checkpoint"):
        load_store(path)


def test_corrupt_checkpoint_raises_loudly(tmp_path):
    path = str(tmp_path / "ckpt.bin")
    with open(path, "wb") as f:
        f.write(b"\x80\x04 garbage that is not a pickle")
    with pytest.raises(Exception):
        load_store(path)


def test_no_temp_files_left_behind(tmp_path):
    store = ClusterStore()
    store.add_node(Node(name="n0", allocatable={"cpu": "1",
                                                "memory": "1Gi"}))
    for k in range(5):
        save_store(store, str(tmp_path / "ckpt.bin"))
    leftovers = [p.name for p in tmp_path.iterdir()
                 if p.name.startswith(".vctpu-ckpt-")]
    assert leftovers == []
