"""Remote-solver split e2e: store/controllers in THIS process, the wave
solver in a real child OS process, the session snapshot crossing as
C++-packed frames (the north-star store<->solver bridge; the reference's
planes likewise talk only through serialized API-server state,
cache.go:492-554)."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from volcano_tpu.scheduler import Scheduler
from volcano_tpu.solver_service import RemoteSolver, SolverServer
from volcano_tpu.synth import preempt_cluster, synthetic_cluster

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_solver(port: int = 0):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "volcano_tpu.solver_service",
         "--port", str(port), "--announce"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=env, cwd=REPO, text=True,
    )
    line = proc.stdout.readline().strip()
    if not line.startswith("SOLVER "):
        proc.kill()
        raise RuntimeError(f"solver did not announce: {line!r}")
    return proc, int(line.split()[1])


@pytest.fixture(scope="module")
def solver_proc():
    proc, port = _spawn_solver()
    yield port
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_two_process_bind_loop(solver_proc):
    """Pods bind through the full two-process loop: encode here, solve
    in the child, commit/bind here."""
    client = RemoteSolver(f"127.0.0.1:{solver_proc}")
    assert client.ping()["op"] == "pong"
    store = synthetic_cluster(n_nodes=12, n_pods=64, gang_size=4, seed=11)
    store.remote_solver = client
    Scheduler(store).run_once()
    store.flush_binds()
    assert len(store.binder.binds) == 64
    assert client.requests >= 1
    assert client.ping()["solves"] >= 1  # the CHILD actually solved
    # Overhead telemetry exists for BASELINE.md.
    assert client.bytes_out > 0 and client.bytes_in > 0
    store.close()


def test_remote_matches_local_placements(solver_proc):
    """Same snapshot, same placements: the bridge is lossless."""
    local = synthetic_cluster(n_nodes=10, n_pods=40, gang_size=4, seed=3)
    Scheduler(local).run_once()
    local.flush_binds()

    remote = synthetic_cluster(n_nodes=10, n_pods=40, gang_size=4, seed=3)
    remote.remote_solver = RemoteSolver(f"127.0.0.1:{solver_proc}")
    Scheduler(remote).run_once()
    remote.flush_binds()

    loc = sorted((b[0], b[1]) for b in local.binder.binds)
    rem = sorted((b[0], b[1]) for b in remote.binder.binds)
    assert loc == rem
    local.close()
    remote.close()


def test_remote_solver_affinity_shape(solver_proc):
    """Affinity count tensors + profile term tables survive the wire."""
    store = synthetic_cluster(
        n_nodes=16, n_pods=96, gang_size=4, zones=4,
        affinity_fraction=0.25, anti_affinity_fraction=0.25, seed=5,
    )
    store.remote_solver = RemoteSolver(f"127.0.0.1:{solver_proc}")
    Scheduler(store).run_once()
    store.flush_binds()
    assert len(store.binder.binds) >= 90
    store.close()


def test_solver_restart_heals():
    """A restarted solver process heals via client reconnect: the cycle
    that hits the dead socket fails, the next one succeeds."""
    proc, port = _spawn_solver()
    client = RemoteSolver(f"127.0.0.1:{port}")
    store = synthetic_cluster(n_nodes=6, n_pods=24, gang_size=4, seed=9)
    store.remote_solver = client
    try:
        Scheduler(store).run_once()
        store.flush_binds()
        assert len(store.binder.binds) == 24
    finally:
        proc.terminate()
        proc.wait(timeout=10)
    # Dead solver: the client raises, the cycle fails, pods stay put.
    store2 = synthetic_cluster(n_nodes=6, n_pods=24, gang_size=4, seed=10)
    store2.remote_solver = client
    os.environ["VOLCANO_TPU_FALLBACK"] = "never"
    try:
        with pytest.raises(Exception):
            Scheduler(store2).run_once()
    finally:
        os.environ.pop("VOLCANO_TPU_FALLBACK", None)
    # New solver at a fresh port: retarget (operator restart semantics)
    proc2, port2 = _spawn_solver()
    try:
        client2 = RemoteSolver(f"127.0.0.1:{port2}")
        store2.remote_solver = client2
        Scheduler(store2).run_once()
        store2.flush_binds()
        assert len(store2.binder.binds) == 24
    finally:
        proc2.terminate()
        proc2.wait(timeout=10)
        store.close()
        store2.close()


def test_in_process_server_roundtrip():
    """SolverServer + RemoteSolver in one process (no subprocess): the
    wire path itself, incl. preempt-shape inputs with releasing
    capacity."""
    import threading

    server = SolverServer(port=0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        store = preempt_cluster(n_nodes=8, n_pending=16, seed=4)
        store.remote_solver = RemoteSolver(f"127.0.0.1:{server.port}")
        conf = """
actions: "enqueue, allocate, preempt, reclaim, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""
        Scheduler(store, conf_str=conf).run_once()
        store.flush_binds()
        assert len(store.evictor.evicts) > 0
        store.close()
    finally:
        server.shutdown()


# --------------------------------- protocol v2: delta wire (ISSUE 10)


def _wire_loop(port, *, cycles=6, seed=31, churn=False, client=None,
               feed_nodes=(0, 1)):
    """Pipelined remote loop over a real socket: returns (binds,
    per-cycle mirror states, per-cycle frame kinds, frame counts,
    fallback counts, client)."""
    import random

    from test_devincr import (
        _churn,
        _mirror_state,
        _partial_feed,
        _reset_uid_counters,
    )

    _reset_uid_counters()
    store = synthetic_cluster(n_nodes=16, n_pods=48, gang_size=4,
                              seed=seed)
    store.pipeline = True
    if client is None:
        client = RemoteSolver(f"127.0.0.1:{port}")
    store.remote_solver = client
    store.cycle_feed = _partial_feed(list(feed_nodes))
    sched = Scheduler(store)
    rng = random.Random(7)
    states, kinds = [], []
    for step in range(cycles):
        sched.run_once()
        states.append(_mirror_state(store))
        kinds.append(client.last_frame_kind)
        if churn and step % 2 == 1:
            _churn(store, rng, step)
    store.flush_binds()
    binds = dict(store.binder.binds)
    counts = dict(client.frame_counts)
    fallbacks = dict(client.wire_fallbacks)
    store.close()
    client.close()
    return binds, states, kinds, counts, fallbacks


def _local_loop(*, cycles=6, seed=31, churn=False, feed_nodes=(0, 1)):
    """The in-process twin of ``_wire_loop`` (same seeds, same churn
    sequence, device solve in THIS process)."""
    import random

    from test_devincr import (
        _churn,
        _mirror_state,
        _partial_feed,
        _reset_uid_counters,
    )

    _reset_uid_counters()
    store = synthetic_cluster(n_nodes=16, n_pods=48, gang_size=4,
                              seed=seed)
    store.pipeline = True
    store.cycle_feed = _partial_feed(list(feed_nodes))
    sched = Scheduler(store)
    rng = random.Random(7)
    states = []
    for step in range(cycles):
        sched.run_once()
        states.append(_mirror_state(store))
        if churn and step % 2 == 1:
            _churn(store, rng, step)
    store.flush_binds()
    binds = dict(store.binder.binds)
    store.close()
    return binds, states


def test_wire_delta_churn_parity_two_process(solver_proc, monkeypatch):
    """ISSUE 10 acceptance: the two-process pipelined remote loop stays
    bind-for-bind AND per-cycle-mirror-state equal to the in-process
    loop across a randomized-churn feed, with delta frames asserted
    engaged (and cheaper than full frames — REC_SAME slots ship no
    payload)."""
    monkeypatch.setenv("VOLCANO_TPU_WIRE", "1")
    binds_r, states_r, kinds, counts, _fb = _wire_loop(
        solver_proc, cycles=10, churn=True)
    binds_l, states_l = _local_loop(cycles=10, churn=True)
    assert binds_r and binds_r == binds_l
    assert states_r == states_l
    assert counts["delta"] >= 2, (kinds, counts)
    assert "delta" in kinds and kinds[0] == "full"


def test_wire_kill_switch_full_frames(solver_proc, monkeypatch):
    """VOLCANO_TPU_WIRE=0: classic v1 frames only (no delta machinery),
    same binds."""
    monkeypatch.setenv("VOLCANO_TPU_WIRE", "0")
    binds_off, states_off, kinds, counts, fallbacks = _wire_loop(
        solver_proc, cycles=6)
    assert counts["delta"] == 0 and counts["full"] >= 6
    assert set(kinds) == {"full"}
    assert fallbacks == {}
    monkeypatch.setenv("VOLCANO_TPU_WIRE", "1")
    binds_on, states_on, _k, counts_on, _fb = _wire_loop(
        solver_proc, cycles=6)
    assert counts_on["delta"] >= 1
    assert binds_on and binds_on == binds_off
    assert states_on == states_off


def test_wire_forced_fallback_lever(solver_proc, monkeypatch):
    """VOLCANO_TPU_WIRE=fallback: the v2 machinery runs but every frame
    ships full through the fallback path, counted reason=forced — the
    bench A/B lever — with identical binds."""
    monkeypatch.setenv("VOLCANO_TPU_WIRE", "fallback")
    binds_fb, states_fb, kinds, counts, fallbacks = _wire_loop(
        solver_proc, cycles=6)
    assert counts["delta"] == 0 and set(kinds) == {"full"}
    assert fallbacks.get("forced", 0) >= 5, fallbacks
    monkeypatch.setenv("VOLCANO_TPU_WIRE", "1")
    binds_on, states_on, _k, _c, _fb = _wire_loop(solver_proc, cycles=6)
    assert binds_on and binds_on == binds_fb
    assert states_on == states_fb


def test_wire_child_restart_heals(monkeypatch):
    """A solver-child restart mid-stream heals via the full-frame
    fallback: the in-flight reply is lost (its rows re-place — never a
    stale solve), the reconnect voids the wire cache so the first frame
    to the new child ships full, and the delta lane re-engages — with
    zero lost pods."""
    from test_devincr import _partial_feed, _reset_uid_counters

    monkeypatch.setenv("VOLCANO_TPU_WIRE", "1")
    # Both children pick their own port (--port 0 + announce) so there
    # is never a probe-then-bind race: the restart derives the new port
    # from the new child's announce and repoints the client, instead of
    # racing other test processes for the freed port.
    proc, port = _spawn_solver()
    _reset_uid_counters()
    store = synthetic_cluster(n_nodes=16, n_pods=48, gang_size=4,
                              seed=37)
    store.pipeline = True
    client = RemoteSolver(f"127.0.0.1:{port}")
    store.remote_solver = client
    store.cycle_feed = _partial_feed([0, 1])
    sched = Scheduler(store)
    kinds = []
    try:
        for _ in range(5):
            sched.run_once()
            kinds.append(client.last_frame_kind)
        assert "delta" in kinds  # lane engaged before the restart
        # Kill the child MID-STREAM: a pipelined solve is in flight.
        proc.terminate()
        proc.wait(timeout=10)
        # Respawn on a fresh OS-assigned port (retry-bounded in case a
        # cold interpreter start flakes) and repoint the client: its
        # dead socket forces a reconnect, which dials host:port anew.
        for attempt in range(3):
            try:
                proc, port = _spawn_solver()
                break
            except RuntimeError:
                if attempt == 2:
                    raise
        client.host, client.port = "127.0.0.1", port
        pre_restart_delta = client.frame_counts["delta"]
        for _ in range(5):
            sched.run_once()
            kinds.append(client.last_frame_kind)
        # The reconnect was counted, the first post-restart frame was
        # full (the new child's mirror starts empty), and deltas
        # resumed against the re-mirrored base.
        assert client.wire_fallbacks.get("reconnect", 0) >= 1
        post = kinds[5:]
        assert post[0] == "full" and "delta" in post, kinds
        assert client.frame_counts["delta"] > pre_restart_delta
        # Zero lost pods: stop the churn feed and drain the pipeline —
        # every pod (including the rows whose in-flight reply died with
        # the old child) must land Bound on a node.
        store.cycle_feed = None
        for _ in range(3):
            sched.run_once()
        store.flush_binds()
        from volcano_tpu.api import TaskStatus

        m = store.mirror
        not_bound = [
            m.p_uid[r] for r in range(m.n_pods)
            if m.p_uid[r] is not None
            and int(m.p_status[r]) != int(TaskStatus.Bound)
        ]
        assert not_bound == [], f"pods lost to the restart: {not_bound}"
        assert all(p.node_name for p in store.pods.values())
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        store.close()
        client.close()


def test_wire_mirror_records_and_resync():
    """Child-side mirror unit: full -> REC_SAME/REC_FULL/REC_DELTA
    materialization, base mismatch -> resync, malformed delta poisons
    the mirror."""
    from volcano_tpu.cache import snapwire as sw
    from volcano_tpu.solver_service import _ResyncNeeded, _WireMirror

    mirror = _WireMirror()
    a0 = np.arange(40, dtype=np.int64).reshape(10, 4)
    a1 = np.zeros(6, np.float32)
    out = mirror.apply(sw, {"gen": 1}, [a0, a1], payload_shared=False)
    assert mirror.gen == 1 and len(out) == 2
    # Delta against a base the mirror does not hold -> resync.
    with pytest.raises(_ResyncNeeded) as ei:
        mirror.apply(sw, {"gen": 2, "base": 99, "recs": [[1], [1]]},
                     [], payload_shared=False)
    assert ei.value.have_gen == 1
    # Valid delta: slot 0 patches rows [2,4), slot 1 ships whole.
    new0 = a0.copy()
    new0[2:4] = -7
    ranges = sw.diff_rows(new0, a0)
    desc = sw.ranges_to_desc(ranges)
    rowpay = sw.gather_rows(new0, ranges)
    new1 = np.ones(6, np.float32)
    out = mirror.apply(
        sw, {"gen": 2, "base": 1,
             "recs": [[sw.REC_DELTA, 0, 1], [sw.REC_FULL, 2]]},
        [desc, rowpay, new1], payload_shared=False)
    assert mirror.gen == 2
    assert np.array_equal(out[0], new0)
    assert np.array_equal(out[1], new1)
    # REC_SAME reuses the mirrored arrays byte-for-byte.
    out2 = mirror.apply(
        sw, {"gen": 3, "base": 2,
             "recs": [[sw.REC_SAME], [sw.REC_SAME]]},
        [], payload_shared=False)
    assert np.array_equal(out2[0], new0)
    assert np.array_equal(out2[1], new1)
    # A malformed delta poisons the mirror; the NEXT delta resyncs.
    bad_desc = np.array([1, 5, 99], np.int64)  # stop past rows
    with pytest.raises(ValueError):
        mirror.apply(
            sw, {"gen": 4, "base": 3,
                 "recs": [[sw.REC_DELTA, 0, 1], [sw.REC_SAME]]},
            [bad_desc, np.zeros(0, np.uint8)], payload_shared=False)
    assert mirror.gen == -1
    with pytest.raises(_ResyncNeeded):
        mirror.apply(
            sw, {"gen": 5, "base": 4,
                 "recs": [[sw.REC_SAME], [sw.REC_SAME]]},
            [], payload_shared=False)


def test_wire_resync_and_ack_mismatch_drop_reply():
    """Client-side defense in depth: a resync reply and a wrong-ack
    reply each void the wire cache and raise ValueError (the pipelined
    fetch treats both as a lost reply — pods re-place, never a stale
    solve)."""
    from volcano_tpu.cache import snapwire as sw

    client = RemoteSolver("127.0.0.1:1")  # never connects
    client._wire.arrays = [np.zeros(4)]
    client._wire.spec = "spec"
    resync = sw.encode_frame([], {"op": "resync", "have_gen": 3})
    with pytest.raises(ValueError, match="resync"):
        client._decode_result(resync)
    assert client.wire_fallbacks.get("gen-mismatch") == 1
    assert client._wire.arrays is None

    arrays_out: list = []
    vals = tuple(np.int32(i) for i in range(7))
    tree = sw.flatten_tree(vals, arrays_out)
    good = sw.encode_frame(
        arrays_out, {"op": "result", "tree": tree, "ack_gen": 2})
    client._wire.arrays = [np.zeros(4)]
    with pytest.raises(ValueError, match="acked gen"):
        client._decode_result(good, expect_gen=3)
    assert client.wire_fallbacks.get("ack-mismatch") == 1
    assert client._wire.arrays is None
    # The SAME reply with the right expectation decodes fine.
    res = client._decode_result(
        sw.encode_frame(arrays_out,
                        {"op": "result", "tree": tree, "ack_gen": 3}),
        expect_gen=3)
    assert int(res.iters) == 4

    # A solver-side error reply ALSO voids the cache (the child
    # poisoned its mirror) — the next frame ships full instead of a
    # doomed delta paying a second lost cycle to the resync round trip.
    client._wire.arrays = [np.zeros(4)]
    err = sw.encode_frame([], {"op": "error", "message": "boom"})
    with pytest.raises(RuntimeError, match="boom"):
        client._decode_result(err)
    assert client.wire_fallbacks.get("child-error") == 1
    assert client._wire.arrays is None and client._wire.pending_reason is None
    # With no delta state mirrored (kill switch off), an error reply
    # does not count a delta-lane fallback.
    with pytest.raises(RuntimeError, match="boom"):
        client._decode_result(err)
    assert client.wire_fallbacks.get("child-error") == 1


def test_wire_v1_child_self_disables(monkeypatch):
    """Version skew (new scheduler, old solver): a reply with NO
    ack_gen means the child speaks protocol v1 — the delta lane
    self-disables for the client's life and frames degrade to classic
    v1 fulls instead of dropping every reply (a permanent outage)."""
    from volcano_tpu.cache import snapwire as sw

    monkeypatch.setenv("VOLCANO_TPU_WIRE", "1")
    client = RemoteSolver("127.0.0.1:1")  # never connects
    arrays_out: list = []
    vals = tuple(np.int32(i) for i in range(7))
    tree = sw.flatten_tree(vals, arrays_out)
    v1_reply = sw.encode_frame(
        arrays_out, {"op": "result", "tree": tree})  # no ack_gen
    # The frame that exposed the skew was full (first wire frame on
    # the connection always is): the solve is valid — keep it.
    client._wire.arrays = [np.zeros(4)]
    client.last_frame_kind = "full"
    res = client._decode_result(v1_reply, expect_gen=1)
    assert int(res.iters) == 4
    assert client._wire_v1_child
    assert client.wire_fallbacks.get("v1-child") == 1
    assert client._wire.arrays is None
    # Subsequent frames ship classic v1 (no wire section, no gen).
    total, parts, kind, gen = client._build_frame(
        (np.arange(4, dtype=np.int32),), np.int32(0), None, None, None)
    assert kind == "full" and gen is None
    man, _ = sw.decode_frame(b"".join(bytes(p) for p in parts))
    assert "wire" not in man
    # Defense in depth: had the skew surfaced on a DELTA frame, the
    # reply is dropped (a v1 child reads descriptors as solve args).
    client2 = RemoteSolver("127.0.0.1:1")
    client2.last_frame_kind = "delta"
    with pytest.raises(ValueError, match="protocol-v1"):
        client2._decode_result(v1_reply, expect_gen=1)
    assert client2._wire_v1_child


def test_wire_shm_v1_child_handshake(monkeypatch):
    """VOLCANO_TPU_SHM=1 against a protocol-v1 solver must not be a
    permanent outage: a v1 child never reads the manifest's shm
    section (it just errors on the empty array list, which is NOT an
    ShmUnavailable reply), so the client probes the pong's advertised
    wire version on connect and degrades to classic v1 TCP frames
    before the first shm payload ships."""
    import socket as socketlib
    import threading

    from volcano_tpu.cache import snapwire as sw
    from volcano_tpu.solver_service import recv_frame, send_frame

    srv = socketlib.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    arrays_out: list = []
    vals = tuple(np.int32(i) for i in range(7))
    tree = sw.flatten_tree(vals, arrays_out)
    result = sw.encode_frame(arrays_out, {"op": "result", "tree": tree})
    seen = {}

    def serve():
        conn, _ = srv.accept()
        ping, _ = sw.decode_frame(recv_frame(conn))
        seen["ping"] = ping.get("op")
        # v1 pong: no "wire" key at all.
        send_frame(conn, sw.encode_frame(
            [], {"op": "pong", "solves": 0, "backend": "cpu"}))
        solve, _ = sw.decode_frame(recv_frame(conn))
        seen["solve"] = solve
        send_frame(conn, result)
        conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    monkeypatch.setenv("VOLCANO_TPU_WIRE", "1")
    monkeypatch.setenv("VOLCANO_TPU_SHM", "1")
    client = RemoteSolver(f"127.0.0.1:{port}")
    res = client.solve((np.arange(4, dtype=np.int32),), np.int32(0),
                       None)
    t.join(timeout=10)
    assert int(res.iters) == 4
    assert client._wire_v1_child and client._shm is None
    assert client.wire_fallbacks.get("shm") == 1
    assert seen["ping"] == "ping"
    # The solve frame the v1 child received was pure v1: no wire or
    # shm sections, payload arrays on the socket.
    assert "wire" not in seen["solve"] and "shm" not in seen["solve"]
    client.close()
    srv.close()


def test_shm_lane_roundtrip_and_unavailable(monkeypatch):
    """Same-host shared-memory lane units: writer->reader view
    roundtrip (incl. segment growth), a bogus segment raises
    ShmUnavailable, and the client disables the lane on the child's
    error reply."""
    from volcano_tpu.cache import snapwire as sw
    from volcano_tpu.solver_service import (
        ShmUnavailable,
        _ShmLane,
        _ShmReader,
    )

    lane = _ShmLane()
    reader = _ShmReader()
    try:
        arrays = [np.arange(100, dtype=np.float32).reshape(10, 10),
                  np.array([3, -1], np.int64), np.zeros(0, np.uint8)]
        section = lane.write(arrays)
        out = reader.arrays(section)
        for a, b in zip(arrays, out):
            assert a.dtype == b.dtype and a.shape == b.shape
            assert np.array_equal(a, b)
        # Growth reallocates a fresh segment; the reader re-attaches by
        # name.
        big = [np.full(1 << 18, 7, np.float64)]
        sec2 = lane.write(big)
        assert sec2["name"] != section["name"]
        out2 = reader.arrays(sec2)
        assert np.array_equal(out2[0], big[0])
        # Hostile slots: out-of-bounds offset must not view past the
        # segment.
        bad = dict(sec2)
        bad["slots"] = [[0, [1 << 24], 0]]
        with pytest.raises(ShmUnavailable):
            reader.arrays(bad)
        # Hostile dims whose int64 product wraps to 0 must not sail
        # through the bounds check (np.prod overflow).
        bad["slots"] = [[0, [1 << 32, 1 << 32], 0]]
        with pytest.raises(ShmUnavailable):
            reader.arrays(bad)
    finally:
        # Views into the segment must die before the mmap can close —
        # including the comparison loop's leaked iteration variables.
        del out, out2, a, b
        reader.close()
        lane.close()
    with pytest.raises(ShmUnavailable):
        _ShmReader().arrays({"name": "vtpu_bogus_nonexistent",
                             "slots": []})
    # Client side: an ShmUnavailable error reply disables the lane and
    # reads as a dropped frame.
    monkeypatch.setenv("VOLCANO_TPU_SHM", "1")
    client = RemoteSolver("127.0.0.1:1")
    assert client._shm is not None
    err = sw.encode_frame(
        [], {"op": "error",
             "message": "ShmUnavailable: cannot attach segment"})
    with pytest.raises(ValueError, match="dropped frame"):
        client._decode_result(err)
    assert client._shm is None
    assert client.wire_fallbacks.get("shm") == 1


def test_wire_shm_two_process_parity(solver_proc, monkeypatch):
    """VOLCANO_TPU_SHM=1 against a real same-host child: payloads ride
    the segment (socket frames shrink to manifests), binds match the
    TCP run, and the lane stays enabled throughout."""
    monkeypatch.setenv("VOLCANO_TPU_WIRE", "1")
    monkeypatch.setenv("VOLCANO_TPU_SHM", "1")
    shm_client = RemoteSolver(f"127.0.0.1:{solver_proc}")
    assert shm_client._shm is not None
    binds_shm, states_shm, kinds, counts, fallbacks = _wire_loop(
        solver_proc, cycles=6, client=shm_client)
    assert "shm" not in fallbacks, fallbacks
    assert counts["delta"] >= 1
    shm_bytes = dict(shm_client.frame_bytes)
    monkeypatch.delenv("VOLCANO_TPU_SHM")
    tcp_client = RemoteSolver(f"127.0.0.1:{solver_proc}")
    binds_tcp, states_tcp, _k, _c, _fb = _wire_loop(
        solver_proc, cycles=6, client=tcp_client)
    tcp_bytes = dict(tcp_client.frame_bytes)
    assert binds_shm == binds_tcp
    assert states_shm == states_tcp
    # The payload-bearing FULL frame shrinks to its manifest on the
    # socket (delta frames are mostly REC_SAME manifests either way).
    assert shm_bytes["full"] < tcp_bytes["full"] / 2, (
        shm_bytes, tcp_bytes)
    assert sum(shm_bytes.values()) < sum(tcp_bytes.values())
