"""Remote-solver split e2e: store/controllers in THIS process, the wave
solver in a real child OS process, the session snapshot crossing as
C++-packed frames (the north-star store<->solver bridge; the reference's
planes likewise talk only through serialized API-server state,
cache.go:492-554)."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from volcano_tpu.scheduler import Scheduler
from volcano_tpu.solver_service import RemoteSolver, SolverServer
from volcano_tpu.synth import preempt_cluster, synthetic_cluster

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_solver():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "volcano_tpu.solver_service",
         "--port", "0", "--announce"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=env, cwd=REPO, text=True,
    )
    line = proc.stdout.readline().strip()
    if not line.startswith("SOLVER "):
        proc.kill()
        raise RuntimeError(f"solver did not announce: {line!r}")
    return proc, int(line.split()[1])


@pytest.fixture(scope="module")
def solver_proc():
    proc, port = _spawn_solver()
    yield port
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_two_process_bind_loop(solver_proc):
    """Pods bind through the full two-process loop: encode here, solve
    in the child, commit/bind here."""
    client = RemoteSolver(f"127.0.0.1:{solver_proc}")
    assert client.ping()["op"] == "pong"
    store = synthetic_cluster(n_nodes=12, n_pods=64, gang_size=4, seed=11)
    store.remote_solver = client
    Scheduler(store).run_once()
    store.flush_binds()
    assert len(store.binder.binds) == 64
    assert client.requests >= 1
    assert client.ping()["solves"] >= 1  # the CHILD actually solved
    # Overhead telemetry exists for BASELINE.md.
    assert client.bytes_out > 0 and client.bytes_in > 0
    store.close()


def test_remote_matches_local_placements(solver_proc):
    """Same snapshot, same placements: the bridge is lossless."""
    local = synthetic_cluster(n_nodes=10, n_pods=40, gang_size=4, seed=3)
    Scheduler(local).run_once()
    local.flush_binds()

    remote = synthetic_cluster(n_nodes=10, n_pods=40, gang_size=4, seed=3)
    remote.remote_solver = RemoteSolver(f"127.0.0.1:{solver_proc}")
    Scheduler(remote).run_once()
    remote.flush_binds()

    loc = sorted((b[0], b[1]) for b in local.binder.binds)
    rem = sorted((b[0], b[1]) for b in remote.binder.binds)
    assert loc == rem
    local.close()
    remote.close()


def test_remote_solver_affinity_shape(solver_proc):
    """Affinity count tensors + profile term tables survive the wire."""
    store = synthetic_cluster(
        n_nodes=16, n_pods=96, gang_size=4, zones=4,
        affinity_fraction=0.25, anti_affinity_fraction=0.25, seed=5,
    )
    store.remote_solver = RemoteSolver(f"127.0.0.1:{solver_proc}")
    Scheduler(store).run_once()
    store.flush_binds()
    assert len(store.binder.binds) >= 90
    store.close()


def test_solver_restart_heals():
    """A restarted solver process heals via client reconnect: the cycle
    that hits the dead socket fails, the next one succeeds."""
    proc, port = _spawn_solver()
    client = RemoteSolver(f"127.0.0.1:{port}")
    store = synthetic_cluster(n_nodes=6, n_pods=24, gang_size=4, seed=9)
    store.remote_solver = client
    try:
        Scheduler(store).run_once()
        store.flush_binds()
        assert len(store.binder.binds) == 24
    finally:
        proc.terminate()
        proc.wait(timeout=10)
    # Dead solver: the client raises, the cycle fails, pods stay put.
    store2 = synthetic_cluster(n_nodes=6, n_pods=24, gang_size=4, seed=10)
    store2.remote_solver = client
    os.environ["VOLCANO_TPU_FALLBACK"] = "never"
    try:
        with pytest.raises(Exception):
            Scheduler(store2).run_once()
    finally:
        os.environ.pop("VOLCANO_TPU_FALLBACK", None)
    # New solver at a fresh port: retarget (operator restart semantics)
    proc2, port2 = _spawn_solver()
    try:
        client2 = RemoteSolver(f"127.0.0.1:{port2}")
        store2.remote_solver = client2
        Scheduler(store2).run_once()
        store2.flush_binds()
        assert len(store2.binder.binds) == 24
    finally:
        proc2.terminate()
        proc2.wait(timeout=10)
        store.close()
        store2.close()


def test_in_process_server_roundtrip():
    """SolverServer + RemoteSolver in one process (no subprocess): the
    wire path itself, incl. preempt-shape inputs with releasing
    capacity."""
    import threading

    server = SolverServer(port=0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        store = preempt_cluster(n_nodes=8, n_pending=16, seed=4)
        store.remote_solver = RemoteSolver(f"127.0.0.1:{server.port}")
        conf = """
actions: "enqueue, allocate, preempt, reclaim, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""
        Scheduler(store, conf_str=conf).run_once()
        store.flush_binds()
        assert len(store.evictor.evicts) > 0
        store.close()
    finally:
        server.shutdown()
