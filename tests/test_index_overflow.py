"""Index-dtype overflow audit for the 100k-node x 1M-pod tier.

Synthetic small-array / large-offset harness: no 1M-row allocation in
tier-1.  Three index families are pinned exact at >= 2^31 logical
ranges:

- the solver's flattened (term x domain) scatter keys — past the int32
  key space the kernel must take the 2-D (term, domain) address form
  (``VOLCANO_TPU_KEYSPACE_MAX`` forces it at toy shapes; binds must be
  bit-identical either way);
- wire range descriptors (protocol v2 deltas): int64 end-to-end, with
  the validator's bounds arithmetic exact at multi-GB logical frames
  and hostile INT64_MAX-adjacent bounds still rejected — in BOTH the
  csrc and the numpy implementations;
- host-side flattened bincount indices (the incremental aggregates):
  the (row * width + col) products are computed in int64 BEFORE the
  multiply, so they stay exact past 2^31.
"""

import numpy as np
import pytest

from volcano_tpu.cache import snapwire


def test_keyspace_gate_default_and_override(monkeypatch):
    from volcano_tpu.ops import wave

    assert wave._keyspace_max() == 2**31 - 2
    monkeypatch.setenv("VOLCANO_TPU_KEYSPACE_MAX", "12345")
    assert wave._keyspace_max() == 12345
    monkeypatch.setenv("VOLCANO_TPU_KEYSPACE_MAX", "junk")
    assert wave._keyspace_max() == 2**31 - 2


def test_forced_2d_keyspace_binds_bit_identical(monkeypatch):
    """The 2-D (term, domain) scatter form — what the kernel takes when
    EW * D crosses 2^31 — produces bit-identical solves at a toy shape
    where both forms compile."""
    import jax

    from volcano_tpu.ops.wave import solve_wave
    from volcano_tpu.synth import solve_args_from_store, synthetic_cluster

    def run():
        store = synthetic_cluster(
            n_nodes=64, n_pods=256, gang_size=4, zones=4,
            affinity_fraction=0.3, anti_affinity_fraction=0.3,
            spread_fraction=0.2, seed=3)
        args, _ = solve_args_from_store(store)
        res = solve_wave(*args, wave=64)
        return jax.device_get((res.assigned, res.pipelined,
                               res.never_ready, res.fit_failed))

    monkeypatch.delenv("VOLCANO_TPU_KEYSPACE_MAX", raising=False)
    flat = run()
    monkeypatch.setenv("VOLCANO_TPU_KEYSPACE_MAX", "1")  # force 2-D
    two_d = run()
    for f, t in zip(flat, two_d):
        assert np.array_equal(np.asarray(f), np.asarray(t))


@pytest.mark.parametrize("native", [True, False])
def test_delta_check_exact_past_2e31(monkeypatch, native):
    """Wire range-descriptor validation at >= 2^31 logical byte offsets:
    totals exact, in-bounds accepted, off-by-one and INT64_MAX-adjacent
    bounds rejected — no array anywhere near that size is allocated
    (the validator only does arithmetic on trusted dims)."""
    if not native:
        monkeypatch.setattr(snapwire, "lib_or_none", lambda: None)
    elif snapwire.lib_or_none() is None:
        pytest.skip("native vcsnap library unavailable")
    rows = 1 << 28  # 268M logical rows x 64 B/row = 16 GiB logical
    row_bytes = 64
    lo, hi = (1 << 27) - 3, (1 << 28) - 1  # offsets cross 2^31 bytes
    desc = np.asarray([2, 5, 9, lo, hi], np.int64)
    total = 4 + (hi - lo)
    got = snapwire.delta_check(desc, rows, row_bytes,
                               total * row_bytes, 7, 7)
    assert got == total
    # One row past the table: rejected.
    bad = np.asarray([1, rows - 1, rows + 1], np.int64)
    assert snapwire.delta_check(bad, rows, row_bytes,
                                2 * row_bytes, 7, 7) == -1
    # INT64_MAX-adjacent hostile bounds: rejected, no wrap to "valid".
    big = np.iinfo(np.int64).max
    hostile = np.asarray([1, big - 1, big], np.int64)
    assert snapwire.delta_check(hostile, rows, row_bytes,
                                row_bytes, 7, 7) == -1
    # Payload-length cross-check stays exact at the big total.
    assert snapwire.delta_check(desc, rows, row_bytes,
                                total * row_bytes - 1, 7, 7) == -1


def test_diff_rows_descriptor_dtype_and_roundtrip():
    """diff_rows -> ranges_to_desc emits int64 descriptors whose
    values survive a gather/apply roundtrip bitwise (including -0.0 /
    NaN payload bits)."""
    old = np.zeros((32, 4), np.float32)
    new = old.copy()
    new[3, 0] = -0.0
    new[3, 1] = np.nan
    new[30] = 7.0
    ranges = snapwire.diff_rows(new, old)
    desc = snapwire.ranges_to_desc(ranges)
    assert desc.dtype == np.int64
    payload = snapwire.gather_rows(new, ranges)
    dst = old.copy()
    snapwire.delta_apply(dst, desc, payload, 1, 1)
    assert np.array_equal(dst.view(np.uint8), new.view(np.uint8))


def test_incremental_flat_bincount_indices_are_int64():
    """The incremental aggregates compute flattened (row, col) bincount
    indices as int64 BEFORE the multiply; a 32-bit product at the same
    magnitudes would wrap negative.  Synthetic large-offset check of
    the exact arithmetic shape the module uses (see
    fastpath_incr._build_aggregates req_scatter)."""
    R = 64
    jb = np.asarray([(1 << 26) + 3], np.int32)  # job row near 2^26
    si = np.asarray([R - 1], np.int64)
    idx = jb.astype(np.int64) * R + si  # the module's index form
    assert idx.dtype == np.int64
    assert int(idx[0]) == ((1 << 26) + 3) * R + R - 1 > 2**31
    # The int32 form WOULD wrap — the property the audit pins.
    with np.errstate(over="ignore"):
        wrapped = (jb * np.int32(R) + si.astype(np.int32))[0]
    assert int(wrapped) != int(idx[0])
    # And the committed code actually takes the int64 form.
    import inspect

    from volcano_tpu import fastpath_incr

    src = inspect.getsource(fastpath_incr)
    assert ".astype(np.int64) * R" in src
