"""Sharded scheduler control plane (ISSUE 16).

Pins the tentpole contracts of volcano_tpu/shard.py:

- two shards over a node-partitioned workload bind-for-bind match the
  single scheduler (ownership filtering loses nothing, zero conflicts);
- a seeded same-node race between shards resolves to exactly ONE bind,
  the loser's row is voided as ``cross-shard-conflict`` and re-placed
  next cycle — never a double-bind, never a lost pod;
- an idle shard steals the most-starved foreign queue via the
  epoch-bumped handoff token, and the donor-keeps-one rule makes the
  handoff ping-pong-stable;
- the conservation auditor stays at zero anomalies under randomized
  cross-shard bind/unbind churn;
- ``VOLCANO_TPU_SHARDS=1`` (the default) is the kill switch: the plain
  pre-sharding ``Scheduler`` path, bitwise identical, with no shard
  state ever attached to the store.

All CPU-only (conftest pins JAX_PLATFORMS=cpu); tier-1.
"""

import numpy as np
import pytest

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    Queue,
    TaskStatus,
)
from volcano_tpu.cache import ClusterStore
from volcano_tpu.metrics import metrics
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.shard import (
    ShardedScheduler,
    make_scheduler,
    shards_from_env,
    stable_shard,
)
from volcano_tpu.synth import synthetic_cluster

pytestmark = pytest.mark.tier1

ST_BOUND = int(TaskStatus.Bound)
ST_PENDING = int(TaskStatus.Pending)


@pytest.fixture(autouse=True)
def _dense_sampling(monkeypatch):
    """Audit every cycle: these tests use the auditor as the referee
    for the optimistic commit protocol, so the sample gate must be
    open."""
    monkeypatch.setenv("VOLCANO_TPU_AUDIT_SAMPLE", "1")


def _qname(shard: int, n_shards: int = 2, avoid=()) -> str:
    """A queue name whose stable hash lands on ``shard`` — probed, not
    hard-coded, so the tests survive any change to the hash."""
    i = 0
    while True:
        name = f"q{i}"
        if name not in avoid and stable_shard(name, n_shards) == shard:
            return name
        i += 1


def _add_gang(store, queue, name, pods, cpu="1", node_selector=None):
    store.add_pod_group(PodGroup(name=name, min_member=pods, queue=queue))
    for k in range(pods):
        kw = {"node_selector": node_selector} if node_selector else {}
        store.add_pod(Pod(
            name=f"{name}-{k}",
            annotations={GROUP_NAME_ANNOTATION: name},
            containers=[{"cpu": cpu, "memory": "1Gi"}],
            **kw,
        ))


def _bind_map(store):
    # Under the store lock: `pods` is a guarded attribute, and the
    # lockdep leg (VOLCANO_TPU_LOCKDEP=1) holds test code to the same
    # contract as the runtime.
    with store._lock:
        return {p.name: p.node_name for p in store.pods.values()}


def _conflict_total():
    return sum(metrics.shard_conflicts.data.values())


def _assert_clean(store):
    a = store.auditor
    assert a.total_anomalies() == 0, [x.to_dict() for x in a.anomalies()]


# ------------------------------------------------------------- parity


def _partitioned_store(qa, qb):
    """Two queues confined to disjoint node sets by selectors: the
    feasible sets never overlap, so the split solves must reproduce the
    joint solve bind-for-bind (one node per zone keeps the placement
    fully forced — score-order differences between a joint and a split
    session cannot leak into the bind map)."""
    store = ClusterStore()
    for zone in ("a", "b"):
        store.add_node(Node(
            name=f"{zone}0",
            allocatable={"cpu": "8", "memory": "32Gi", "pods": 64},
            labels={"zone": zone},
        ))
    store.add_queue(Queue(name=qa, weight=1))
    store.add_queue(Queue(name=qb, weight=1))
    for zone, q in (("a", qa), ("b", qb)):
        for g in range(2):
            _add_gang(store, q, f"g-{zone}-{g}", pods=3,
                      node_selector={"zone": zone})
    store.pipeline = True
    return store


def test_two_shard_parity_on_partitioned_workload():
    qa = _qname(0)
    qb = _qname(1)
    single = _partitioned_store(qa, qb)
    sharded = _partitioned_store(qa, qb)

    sched1 = Scheduler(single)
    for _ in range(4):
        sched1.run_once()
    single.flush_binds()

    before = _conflict_total()
    sched2 = ShardedScheduler(sharded, shards=2)
    for _ in range(4):
        sched2.run_once()
    sharded.flush_binds()

    want = _bind_map(single)
    got = _bind_map(sharded)
    assert all(want.values()), want  # the single path bound everything
    assert got == want  # bind-for-bind parity
    # A partitioned workload never races: the commit gate stayed quiet.
    assert _conflict_total() == before
    assert all(ctx.conflicts == 0 for ctx in sched2.shards)
    snap = sched2.debug_snapshot()
    assert snap["shards"] == 2
    assert all(s["cycles"] == 4 for s in snap["per_shard"])
    _assert_clean(single)
    _assert_clean(sharded)


def test_shard_filter_is_a_partition_of_the_session():
    """Every job lands on exactly one shard: the per-shard session_jobs
    sets are disjoint and their union is the full session."""
    qa = _qname(0)
    qb = _qname(1)
    store = _partitioned_store(qa, qb)
    sched = ShardedScheduler(store, shards=2)
    sched.run_once()
    recs = store.flight.recent()
    considered = {}
    for r in recs:
        if r.session.endswith("@s0"):
            considered[0] = r.pods_considered
        elif r.session.endswith("@s1"):
            considered[1] = r.pods_considered
    # 12 pods, half per queue, one queue per shard.
    assert considered == {0: 6, 1: 6}


# ----------------------------------------------------- same-node race


def test_same_node_race_one_bind_loser_replaced():
    """Both shards solve the same cap-1 node in the same overlap: the
    second commit's rows are voided as ``cross-shard-conflict`` and the
    loser re-places onto the spare node next cycle — exactly one bind
    per node, zero lost pods."""
    qa = _qname(0)
    qb = _qname(1)
    store = ClusterStore()
    for i in range(2):
        store.add_node(Node(
            name=f"n{i}",
            allocatable={"cpu": "1", "memory": "8Gi", "pods": 8},
        ))
    store.add_queue(Queue(name=qa, weight=1))
    store.add_queue(Queue(name=qb, weight=1))
    _add_gang(store, qa, "ga", pods=1)
    _add_gang(store, qb, "gb", pods=1)
    store.pipeline = True

    before = _conflict_total()
    sched = ShardedScheduler(store, shards=2)
    for _ in range(6):
        sched.run_once()
    store.flush_binds()

    binds = _bind_map(store)
    assert all(binds.values()), binds  # the loser re-placed: no lost pod
    # cap-1 nodes: the race resolved to exactly one bind per node.
    assert sorted(binds.values()) == ["n0", "n1"]
    # The losing rows were attributed to the optimistic protocol.
    assert _conflict_total() > before
    assert sum(ctx.conflicts for ctx in sched.shards) >= 1
    dropped = {}
    for r in store.flight.recent():
        for reason, n in r.drop_reasons.items():
            dropped[reason] = dropped.get(reason, 0) + n
    assert dropped.get("cross-shard-conflict", 0) >= 1
    _assert_clean(store)


# ------------------------------------------------------ work stealing


def test_idle_shard_steals_most_starved_queue():
    qx = _qname(0)
    qy = _qname(0, avoid={qx})
    store = ClusterStore()
    for i in range(2):
        store.add_node(Node(
            name=f"n{i}",
            allocatable={"cpu": "8", "memory": "32Gi", "pods": 64},
        ))
    store.add_queue(Queue(name=qx, weight=1))
    store.add_queue(Queue(name=qy, weight=1))
    _add_gang(store, qx, "big", pods=4)    # the starved backlog
    _add_gang(store, qy, "small", pods=2)  # the queue the donor keeps

    steals_before = sum(metrics.shard_steals.data.values())
    sched = ShardedScheduler(store, shards=2)
    thief = sched.schedulers[1]
    # Only the idle shard runs: it owns neither queue, so it must steal
    # the larger backlog (qx) and bind it itself.
    thief.run_once()
    thief.run_once()
    store.flush_binds()

    with store._lock:
        assert sched.table.epoch == 1
    assert sched.table.snapshot()["overrides"] == {qx: 1}
    assert sched.shards[1].steals == 1
    assert sum(metrics.shard_steals.data.values()) == steals_before + 1
    binds = _bind_map(store)
    assert all(binds[f"big-{k}"] for k in range(4))  # stolen queue ran
    assert not any(binds[f"small-{k}"] for k in range(2))  # kept queue

    # Ping-pong guard: qx is drained, so the thief is idle again — but
    # the donor's ONLY remaining pending queue (qy) must not move.
    thief.run_once()
    with store._lock:
        assert sched.table.epoch == 1
    assert sched.shards[1].steals == 1

    # Moving a queue back to its base owner clears the override: the
    # table converges to empty under balanced load.
    with store._lock:
        epoch = sched.table.steal_queue(qx, 0)
    assert epoch == 2
    assert sched.table.snapshot()["overrides"] == {}
    _assert_clean(store)


# ------------------------------------------------- cross-shard churn


def test_cross_shard_churn_auditor_clean():
    """Randomized bind/unbind churn across two shards over a shared
    node pool: conflicts are expected, anomalies are not — the
    conservation auditor referees the optimistic protocol every
    cycle."""
    store = synthetic_cluster(n_nodes=12, n_pods=64, gang_size=4,
                              n_queues=4, seed=11)
    store.pipeline = True
    rng = np.random.default_rng(11)

    def feed(fc):
        m = fc.m
        rows = np.flatnonzero(
            (m.p_status[:fc.Pn] == ST_BOUND) & m.p_alive[:fc.Pn]
        )
        if len(rows) >= 4:
            take = rng.choice(rows, size=len(rows) // 4, replace=False)
            fc._unbind_rows(np.sort(take))

    store.cycle_feed = feed
    sched = ShardedScheduler(store, shards=2)
    for _ in range(30):
        sched.run_once()
    store.flush_binds()

    _assert_clean(store)
    snap = sched.debug_snapshot()
    assert [s["cycles"] for s in snap["per_shard"]] == [30, 30]
    # Conservation at the store edge: every pod is still accounted for
    # (pending or bound), none lost to a voided commit.
    m = store.mirror
    alive = m.p_alive[:m.n_pods]
    status = m.p_status[:m.n_pods][alive]
    assert np.isin(status, [ST_PENDING, ST_BOUND]).all()


# --------------------------------------------------------- kill switch


def test_env_knob_and_factory(monkeypatch):
    monkeypatch.delenv("VOLCANO_TPU_SHARDS", raising=False)
    assert shards_from_env() == 1
    monkeypatch.setenv("VOLCANO_TPU_SHARDS", "4")
    assert shards_from_env() == 4
    monkeypatch.setenv("VOLCANO_TPU_SHARDS", "zap")
    assert shards_from_env() == 1  # warns, never crashes the service

    store = synthetic_cluster(n_nodes=2, n_pods=4, gang_size=2, seed=1)
    monkeypatch.setenv("VOLCANO_TPU_SHARDS", "2")
    sched = make_scheduler(store)
    assert isinstance(sched, ShardedScheduler)
    assert sched.n_shards == 2
    monkeypatch.setenv("VOLCANO_TPU_SHARDS", "1")
    single = make_scheduler(synthetic_cluster(n_nodes=2, n_pods=4,
                                              gang_size=2, seed=1))
    assert isinstance(single, Scheduler)
    assert not isinstance(single, ShardedScheduler)


def test_kill_switch_is_bitwise_identical():
    """shards=1 must be the pre-sharding code path itself: same binds,
    same mirror planes, no shard state ever attached to the store."""
    runs = []
    for factory in (
        lambda s: Scheduler(s),             # the pre-PR construction
        lambda s: make_scheduler(s, shards=1),
    ):
        store = synthetic_cluster(n_nodes=8, n_pods=32, gang_size=4,
                                  n_queues=2, seed=5)
        store.pipeline = True
        sched = factory(store)
        for _ in range(4):
            sched.run_once()
        store.flush_binds()
        runs.append(store)

    a, b = runs
    ma, mb = a.mirror, b.mirror
    assert ma.n_pods == mb.n_pods
    for plane in ("p_alive", "p_status", "p_node", "p_job"):
        assert np.array_equal(
            getattr(ma, plane)[:ma.n_pods], getattr(mb, plane)[:mb.n_pods]
        ), plane
    assert _bind_map(a) == _bind_map(b)
    # The unsharded path never touches the sharding machinery.
    for store in runs:
        assert getattr(store, "shard_table") is None
        assert store._shard_inflight == {}
        assert store.mirror.shard_commit_seq == 0
