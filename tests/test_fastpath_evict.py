"""Fast-path preempt/reclaim parity with the object-session path."""

import os

import pytest

from volcano_tpu.scheduler import Scheduler
from volcano_tpu.synth import preempt_cluster, synthetic_cluster

CONF_PREEMPT = """
actions: "enqueue, allocate, preempt, reclaim, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


def _run(store, fast: bool):
    os.environ["VOLCANO_TPU_FASTPATH"] = "1" if fast else "0"
    try:
        Scheduler(store, conf_str=CONF_PREEMPT).run_once()
    finally:
        os.environ.pop("VOLCANO_TPU_FASTPATH", None)
    return store


def _state(store):
    return (
        dict(store.binder.binds),
        sorted(store.evictor.evicts),
        {uid: pg.status.phase
         for uid, pg in sorted(store.pod_groups.items())},
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_preempt_parity(seed):
    a = _run(preempt_cluster(n_nodes=8, n_pending=12, seed=seed), fast=False)
    b = _run(preempt_cluster(n_nodes=8, n_pending=12, seed=seed), fast=True)
    sa, sb = _state(a), _state(b)
    assert sb[0] == sa[0]  # binds
    assert sb[1] == sa[1]  # evictions
    assert sb[2] == sa[2]  # phases


@pytest.mark.parametrize("seed", [0, 1])
def test_preempt_parity_multiqueue(seed):
    kw = dict(n_nodes=10, n_pods=40, gang_size=4, n_queues=3,
              queue_weights=(1, 2, 4), seed=seed)
    a = _run(synthetic_cluster(**kw), fast=False)
    b = _run(synthetic_cluster(**kw), fast=True)
    sa, sb = _state(a), _state(b)
    assert sb[0] == sa[0]
    assert sb[1] == sa[1]
    assert sb[2] == sa[2]


def test_preempt_fast_path_used(monkeypatch):
    import volcano_tpu.fastpath_evict as fe

    called = {}
    orig = fe.FastEvictor.preempt

    def spy(self):
        called["yes"] = True
        return orig(self)

    monkeypatch.setattr(fe.FastEvictor, "preempt", spy)
    store = preempt_cluster(n_nodes=4, n_pending=6, seed=0)
    Scheduler(store, conf_str=CONF_PREEMPT).run_once()
    assert called.get("yes")


CONF_INTERLEAVED = CONF_PREEMPT.replace(
    '"enqueue, allocate, preempt, reclaim, backfill"',
    '"enqueue, preempt, allocate, reclaim, backfill"',
)


@pytest.mark.parametrize("seed", [0, 1])
def test_evictor_resync_across_interleaved_allocate(seed):
    """An allocate action between two evict actions mutates n_idle and
    n_ntasks; the evictor created by the earlier action must resync its
    future-idle/slot caches instead of overestimating capacity."""
    a_store = preempt_cluster(n_nodes=8, n_pending=12, seed=seed)
    b_store = preempt_cluster(n_nodes=8, n_pending=12, seed=seed)
    os.environ["VOLCANO_TPU_FASTPATH"] = "0"
    try:
        Scheduler(a_store, conf_str=CONF_INTERLEAVED).run_once()
    finally:
        os.environ.pop("VOLCANO_TPU_FASTPATH", None)
    os.environ["VOLCANO_TPU_FASTPATH"] = "1"
    try:
        Scheduler(b_store, conf_str=CONF_INTERLEAVED).run_once()
    finally:
        os.environ.pop("VOLCANO_TPU_FASTPATH", None)
    assert _state(b_store) == _state(a_store)
