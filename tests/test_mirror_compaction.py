"""Mirror compaction semantics.

``maybe_compact`` rebuilds the pod table without tombstones once dead
rows dominate (>= 4096 rows, >= half dead).  Everything that indexes by
row — p_row, bind keys, job links, the p_pod_nones tombstone counter —
must survive the remap, and subsequent scheduling must behave as if the
compaction never happened.
"""

import numpy as np

from volcano_tpu.api import GROUP_NAME_ANNOTATION, Node, Pod, PodGroup, PodPhase
from volcano_tpu.cache import ClusterStore
from volcano_tpu.scheduler import Scheduler


def churned_store(n_keep=64):
    """Create + delete enough pods to cross the compaction threshold,
    keeping ``n_keep`` running pods alive."""
    s = ClusterStore()
    for i in range(8):
        s.add_node(Node(name=f"n{i}",
                        allocatable={"cpu": "64", "memory": "128Gi",
                                     "pods": 256}))
    s.add_pod_group(PodGroup(name="keep", min_member=1))
    keepers = []
    for k in range(n_keep):
        pod = Pod(name=f"keep-{k}",
                  annotations={GROUP_NAME_ANNOTATION: "keep"},
                  containers=[{"cpu": "1", "memory": "1Gi"}],
                  phase=PodPhase.Running, node_name=f"n{k % 8}")
        s.add_pod(pod)
        keepers.append(pod)
    s.add_pod_group(PodGroup(name="churn", min_member=1))
    # Tombstone far more rows than survive.
    for k in range(4400):
        pod = Pod(name=f"churn-{k}",
                  annotations={GROUP_NAME_ANNOTATION: "churn"},
                  containers=[{"cpu": "1", "memory": "1Gi"}])
        s.add_pod(pod)
        s.delete_pod(pod)
    return s, keepers


def test_compaction_triggers_and_remaps():
    s, keepers = churned_store()
    m = s.mirror
    assert len(m.p_uid) < 4096, "compaction did not trigger"
    # Compaction fires mid-churn; deletes after it leave tombstones, and
    # the counter must agree with them exactly (it was reset by the
    # rebuild and re-counted only post-compaction deletes).
    assert m.p_pod_nones == m.n_dead
    assert sum(1 for p in m.p_pod if p is None) == m.p_pod_nones
    # Every survivor is findable at its remapped row with intact state.
    for pod in keepers:
        row = m.p_row[pod.uid]
        assert m.p_uid[row] == pod.uid
        assert m.p_pod[row] is s.pods[pod.uid]
        assert m.n_name[m.p_node[row]] == pod.node_name
    # Node accounting unchanged.
    used = sum(n.used.milli_cpu for n in s.nodes.values())
    assert used == len(keepers) * 1000


def test_scheduling_after_compaction():
    """A fresh gang scheduled after compaction binds normally (rows,
    CSR columns, and job links all remapped coherently)."""
    s, _ = churned_store()
    s.add_pod_group(PodGroup(name="late", min_member=4))
    for k in range(4):
        s.add_pod(Pod(name=f"late-{k}",
                      annotations={GROUP_NAME_ANNOTATION: "late"},
                      containers=[{"cpu": "2", "memory": "2Gi"}]))
    Scheduler(s).run_once()
    late = [p for p in s.pods.values()
            if p.annotations.get(GROUP_NAME_ANNOTATION) == "late"]
    assert len(late) == 4
    assert all(p.node_name for p in late)


def test_compaction_preserves_affinity_term_members():
    """Term membership (inter-pod affinity candidates) survives the row
    remap: an anti-affinity gang placed after churn still spreads."""
    s = ClusterStore()
    for i in range(6):
        s.add_node(Node(name=f"n{i}",
                        allocatable={"cpu": "32", "memory": "64Gi",
                                     "pods": 256}))
    from volcano_tpu.api import AffinityTerm

    # Churn past the threshold first.
    s.add_pod_group(PodGroup(name="churn", min_member=1))
    for k in range(4400):
        pod = Pod(name=f"churn-{k}",
                  annotations={GROUP_NAME_ANNOTATION: "churn"},
                  containers=[{"cpu": "1", "memory": "1Gi"}])
        s.add_pod(pod)
        s.delete_pod(pod)
    s.add_pod_group(PodGroup(name="anti", min_member=3))
    for k in range(3):
        s.add_pod(Pod(
            name=f"anti-{k}",
            labels={"app": "anti"},
            annotations={GROUP_NAME_ANNOTATION: "anti"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
            anti_affinity=[AffinityTerm(
                match_labels={"app": "anti"},
                topology_key="kubernetes.io/hostname",
            )],
        ))
    Scheduler(s).run_once()
    placed = [p.node_name for p in s.pods.values()
              if p.annotations.get(GROUP_NAME_ANNOTATION) == "anti"]
    assert all(placed)
    assert len(set(placed)) == 3, placed
