"""Device-lane incrementality (ISSUE 9): persistent static planes,
warm-started shortlists, and null-delta fast cycles.

The acceptance bar is BIT-FOR-BIT: with ``VOLCANO_TPU_DEVINCR=1``,
binds/phases/mirror state must equal the ``=0`` path across randomized
churn — including the mesh-sharded and remote-solver paths — and every
invalidation edge (class-set change, profile-set change, node-liveness
flip, compaction, dirty-cap overflow) must demonstrably fall back to a
full recompute.
"""

import itertools
import os
import random

import numpy as np
import pytest

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    TaskStatus,
)
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.synth import synthetic_cluster

pytestmark = pytest.mark.tier1

ST_BOUND = int(TaskStatus.Bound)


def _reset_uid_counters():
    import volcano_tpu.api.spec as spec

    spec._uid_counter = itertools.count(1)
    spec._ts_counter = itertools.count(1)


def _partial_feed(node_rows):
    """Re-pend only rows bound to ``node_rows`` — a sparse steady-state
    dirty set, the warm path's home turf."""

    def feed(fc):
        m = fc.m
        rows = np.flatnonzero(
            (m.p_status[:fc.Pn] == ST_BOUND) & m.p_alive[:fc.Pn]
        )
        if len(rows):
            sel = rows[np.isin(m.p_node[rows], node_rows)]
            if len(sel):
                fc._unbind_rows(sel)

    return feed


def _mirror_state(store):
    m = store.mirror
    return tuple(
        (m.p_uid[r], int(m.p_status[r]), m.p_node_name[r])
        for r in range(m.n_pods) if m.p_uid[r] is not None
    )


def _churn(store, rng, step):
    """Randomized mutation batch (name-keyed — twin runs must see the
    identical op sequence)."""
    op = rng.choice(["add_gang", "delete_pod", "node_flap", "add_pods",
                     "nothing"])
    if op == "add_gang":
        name = f"churn-{step}"
        store.add_pod_group(PodGroup(name=name, min_member=2))
        for i in range(2):
            store.add_pod(Pod(
                name=f"{name}-{i}",
                annotations={GROUP_NAME_ANNOTATION: name},
                containers=[{"cpu": "1", "memory": "1Gi"}],
            ))
    elif op == "delete_pod":
        pods = sorted(store.pods.values(), key=lambda p: p.name)
        if pods:
            store.delete_pod(pods[rng.randrange(len(pods))])
    elif op == "node_flap":
        names = sorted(store.mirror.n_row)
        if names:
            name = names[rng.randrange(len(names))]
            if rng.random() < 0.5:
                store.delete_node(name)
            else:
                store.add_node(Node(
                    name=name,
                    allocatable={"cpu": "64", "memory": "256Gi",
                                 "pods": 256},
                ))
    elif op == "add_pods":
        name = f"solo-{step}"
        store.add_pod_group(PodGroup(name=name, min_member=1))
        store.add_pod(Pod(
            name=f"{name}-0",
            annotations={GROUP_NAME_ANNOTATION: name},
            containers=[{"cpu": "2", "memory": "2Gi"}],
        ))


def _twin_run(devincr: bool, monkeypatch, *, mesh=None, churn=True,
              cycles=10, seed=13, **cluster_kw):
    monkeypatch.setenv("VOLCANO_TPU_DEVINCR", "1" if devincr else "0")
    _reset_uid_counters()
    kw = dict(n_nodes=24, n_pods=72, gang_size=4, seed=seed)
    kw.update(cluster_kw)
    store = synthetic_cluster(**kw)
    store.pipeline = True
    if mesh is not None:
        store.solve_mesh = mesh
    store.cycle_feed = _partial_feed([0, 1])
    sched = Scheduler(store)
    rng = random.Random(7)
    states = []
    for step in range(cycles):
        sched.run_once()
        states.append(_mirror_state(store))
        if churn and step % 2 == 1:
            _churn(store, rng, step)
    dv = getattr(store, "_devincr_cache", None)
    counts = dict(dv.counts) if dv is not None else {}
    store.flush_binds()
    binds = dict(store.binder.binds)
    phases = {uid: pg.status.phase
              for uid, pg in sorted(store.pod_groups.items())}
    store.close()
    return binds, phases, states, counts


def test_churn_parity_devincr_on_off(monkeypatch):
    """Randomized churn over a pipelined feed loop: binds, PodGroup
    phases, and the full per-cycle mirror-state sequence are bit-for-bit
    equal between incremental-on and DEVINCR=0 — and the on-run must
    actually take the warm path."""
    b1, p1, s1, c1 = _twin_run(True, monkeypatch)
    b0, p0, s0, c0 = _twin_run(False, monkeypatch)
    assert b1 == b0
    assert p1 == p0
    assert s1 == s0
    assert c1.get("warm", 0) >= 1, f"warm path never engaged: {c1}"
    assert c0 == {}, "DEVINCR=0 must not touch the lane"


def test_churn_parity_mesh_sharded(monkeypatch):
    """Same parity bar on the mesh path (virtual CPU mesh): the
    replicated devincr planes + warm kernel must not perturb the
    sharded solve."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    from volcano_tpu.parallel import make_mesh

    mesh = make_mesh(4)
    b1, p1, s1, c1 = _twin_run(True, monkeypatch, mesh=mesh, cycles=8,
                               n_nodes=16, n_pods=48)
    b0, p0, s0, c0 = _twin_run(False, monkeypatch, mesh=mesh, cycles=8,
                               n_nodes=16, n_pods=48)
    assert b1 == b0
    assert p1 == p0
    assert s1 == s0
    assert c1.get("warm", 0) >= 1, f"warm path never engaged: {c1}"


def test_affinity_churn_parity(monkeypatch):
    """Affinity workloads: the cnt0 content token invalidates warm
    reuse whenever resident term counts move, so parity must hold with
    inter-pod terms in play."""
    b1, p1, s1, c1 = _twin_run(
        True, monkeypatch, cycles=8, seed=5,
        affinity_fraction=0.3, anti_affinity_fraction=0.1,
        spread_fraction=0.2, zones=2,
    )
    b0, p0, s0, c0 = _twin_run(
        False, monkeypatch, cycles=8, seed=5,
        affinity_fraction=0.3, anti_affinity_fraction=0.1,
        spread_fraction=0.2, zones=2,
    )
    assert b1 == b0
    assert p1 == p0
    assert s1 == s0


# ------------------------------------------------- invalidation edges


def _steady_store(monkeypatch, n_nodes=16, n_pods=48):
    monkeypatch.setenv("VOLCANO_TPU_DEVINCR", "1")
    _reset_uid_counters()
    store = synthetic_cluster(n_nodes=n_nodes, n_pods=n_pods,
                              gang_size=4, seed=3)
    store.pipeline = True
    store.cycle_feed = _partial_feed([0])
    sched = Scheduler(store)
    # Warm the lane: fill + reach steady warm state.
    for _ in range(4):
        sched.run_once()
    dv = store._devincr_cache
    assert dv.last_mode == "warm", dv.counts
    return store, sched, dv


def _modes_after(sched, dv, n=2):
    modes = []
    for _ in range(n):
        sched.run_once()
        modes.append(dv.last_mode)
    return modes


def test_invalidation_node_relabel_falls_back(monkeypatch):
    """A node relabel changes the class-table signature (and epoch):
    the next solve must full-recompute, then warm again."""
    store, sched, dv = _steady_store(monkeypatch)
    store.add_node(Node(
        name=sorted(store.mirror.n_row)[2],
        allocatable={"cpu": "64", "memory": "256Gi", "pods": 256},
        labels={"relabelled": "yes"},
    ))
    modes = _modes_after(sched, dv, 3)
    assert modes[0] == "full", modes
    assert "warm" in modes[1:], modes
    store.close()


def test_invalidation_profile_set_change_falls_back(monkeypatch):
    """A new pending profile rebuilds the encode cache (profile
    generation bump): statics + warm candidates are stale -> full."""
    store, sched, dv = _steady_store(monkeypatch)
    builds0 = dv.static_builds
    store.add_pod_group(PodGroup(name="newprof", min_member=1))
    store.add_pod(Pod(
        name="newprof-0",
        annotations={GROUP_NAME_ANNOTATION: "newprof"},
        containers=[{"cpu": "3", "memory": "3Gi"}],  # distinct profile
    ))
    modes = _modes_after(sched, dv, 1)
    assert modes[0] == "full", modes
    assert dv.static_builds > builds0, "static planes not rebuilt"
    store.close()


def test_invalidation_node_liveness_flip_falls_back(monkeypatch):
    """A node deletion flips liveness (and epoch): full recompute."""
    store, sched, dv = _steady_store(monkeypatch)
    store.delete_node(sorted(store.mirror.n_row)[-1])
    modes = _modes_after(sched, dv, 1)
    assert modes[0] == "full", modes
    store.close()


def test_invalidation_compaction_falls_back(monkeypatch):
    """A pod-table compaction renumbers rows (compact_gen): the warm
    key breaks, the derive full-rebuilds (poisoning the dirty
    accumulator), and any in-flight solve voids -> full.  The gen bump
    is synthetic (real compaction needs 4096+ tombstoned rows —
    mechanics covered by test_mirror_compaction); the invalidation
    contract keys on the GENERATION, which is what this pins."""
    store, sched, dv = _steady_store(monkeypatch, n_pods=48)
    with store._lock:
        store.mirror.compact_gen += 1
    modes = _modes_after(sched, dv, 1)
    assert modes[0] == "full", modes
    # And the lane recovers to warm afterwards.
    assert "warm" in _modes_after(sched, dv, 2)
    store.close()


def test_invalidation_dirty_cap_overflow_falls_back(monkeypatch):
    """Past VOLCANO_TPU_DIRTY_CAP the dirty superset is unprovable:
    every solve takes the full re-rank (and stays correct)."""
    monkeypatch.setenv("VOLCANO_TPU_DIRTY_CAP", "1")
    monkeypatch.setenv("VOLCANO_TPU_DEVINCR", "1")
    _reset_uid_counters()
    store = synthetic_cluster(n_nodes=16, n_pods=48, gang_size=4,
                              seed=3)
    store.pipeline = True
    store.cycle_feed = _partial_feed([0])
    sched = Scheduler(store)
    for _ in range(5):
        sched.run_once()
    dv = store._devincr_cache
    assert dv.counts["warm"] == 0, dv.counts
    assert dv.counts["full"] >= 1, dv.counts
    store.flush_binds()
    assert len(store.binder.binds) >= 1
    store.close()


# --------------------------------------------------- null-delta cycles


def test_null_delta_skips_and_resumes(monkeypatch):
    """An idle pipelined loop records skip-cycles in the flight
    recorder, dispatches zero solves, and resumes an ordinary solve on
    the first mutation."""
    monkeypatch.setenv("VOLCANO_TPU_DEVINCR", "1")
    _reset_uid_counters()
    store = synthetic_cluster(n_nodes=8, n_pods=24, gang_size=4, seed=5)
    store.pipeline = True
    sched = Scheduler(store)
    for _ in range(2):
        sched.run_once()
    # A pending-but-unschedulable gang keeps the pending set non-empty
    # (otherwise the lane early-outs before the skip check matters).
    store.add_pod_group(PodGroup(name="big", min_member=1))
    store.add_pod(Pod(
        name="big-0", annotations={GROUP_NAME_ANNOTATION: "big"},
        containers=[{"cpu": "512", "memory": "512Gi"}],
    ))
    sched.run_once()   # dispatches the (failing) solve
    sched.run_once()   # commits the empty result
    dv = store._devincr_cache
    seq0 = store._solve_seq
    skips0 = dv.counts["skip"]
    for _ in range(3):
        sched.run_once()
    assert store._solve_seq == seq0, "idle cycles dispatched solves"
    assert dv.counts["skip"] == skips0 + 3, dv.counts
    recs = store.flight.recent()[-3:]
    for r in recs:
        assert any("null-delta" in e for e in r.device_events), \
            r.device_events
        assert r.dispatched_solve_id is None
    # First mutation resumes an ordinary solve and binds the new pod.
    store.add_pod_group(PodGroup(name="ok", min_member=1))
    store.add_pod(Pod(
        name="ok-0", annotations={GROUP_NAME_ANNOTATION: "ok"},
        containers=[{"cpu": "1", "memory": "1Gi"}],
    ))
    sched.run_once()
    assert store._solve_seq > seq0, "mutation did not resume dispatch"
    sched.run_once()
    store.flush_binds()
    assert any("ok-0" in k for k in store.binder.binds)
    store.close()


def test_null_delta_skip_counts_metric(monkeypatch):
    """The skip decisions land in
    volcano_device_incremental_solves_total{mode=skip}."""
    from volcano_tpu.metrics import metrics

    monkeypatch.setenv("VOLCANO_TPU_DEVINCR", "1")
    _reset_uid_counters()
    store = synthetic_cluster(n_nodes=8, n_pods=16, gang_size=4, seed=9)
    store.pipeline = True
    sched = Scheduler(store)
    for _ in range(2):
        sched.run_once()
    store.add_pod_group(PodGroup(name="big", min_member=1))
    store.add_pod(Pod(
        name="big-0", annotations={GROUP_NAME_ANNOTATION: "big"},
        containers=[{"cpu": "512", "memory": "512Gi"}],
    ))
    sched.run_once()
    sched.run_once()
    text0 = metrics.expose_text()
    sched.run_once()
    text1 = metrics.expose_text()

    def count(text):
        for line in text.splitlines():
            if ("device_incremental_solves_total" in line
                    and 'mode="skip"' in line):
                return float(line.rsplit(" ", 1)[1])
        return 0.0

    assert count(text1) == count(text0) + 1
    store.close()


def test_kill_switch_disables_everything(monkeypatch):
    """VOLCANO_TPU_DEVINCR=0: no skip, no warm, no static planes — and
    the lane's store slot stays untouched by the solve path."""
    monkeypatch.setenv("VOLCANO_TPU_DEVINCR", "0")
    _reset_uid_counters()
    store = synthetic_cluster(n_nodes=8, n_pods=24, gang_size=4, seed=5)
    store.pipeline = True
    sched = Scheduler(store)
    for _ in range(4):
        sched.run_once()
    dv = getattr(store, "_devincr_cache", None)
    assert dv is None or (dv.counts["warm"] == 0
                          and dv.counts["skip"] == 0)
    store.flush_binds()
    assert len(store.binder.binds) == 24
    store.close()


# ------------------------------------------------------- remote solver


def test_remote_solver_devincr_parity(monkeypatch):
    """The remote child keeps its own persistent planes keyed by the
    frame manifest's tokens: pipelined remote binds with DEVINCR=1 must
    equal the local DEVINCR=0 run, and the child must report a warm
    decision once steady."""
    import subprocess

    from test_remote_solver import _spawn_solver

    from volcano_tpu.solver_service import RemoteSolver

    proc, port = _spawn_solver()
    try:
        monkeypatch.setenv("VOLCANO_TPU_DEVINCR", "1")
        _reset_uid_counters()
        store = synthetic_cluster(n_nodes=12, n_pods=36, gang_size=4,
                                  seed=21)
        store.pipeline = True
        store.remote_solver = RemoteSolver(f"127.0.0.1:{port}")
        store.cycle_feed = _partial_feed([0, 1])
        sched = Scheduler(store)
        states_r = []
        modes = []
        for _ in range(7):
            sched.run_once()
            states_r.append(_mirror_state(store))
            modes.append(store.remote_solver.last_devincr_mode)
        store.flush_binds()
        binds_r = dict(store.binder.binds)
        store.close()

        monkeypatch.setenv("VOLCANO_TPU_DEVINCR", "0")
        _reset_uid_counters()
        store = synthetic_cluster(n_nodes=12, n_pods=36, gang_size=4,
                                  seed=21)
        store.pipeline = True
        store.cycle_feed = _partial_feed([0, 1])
        sched = Scheduler(store)
        states_l = []
        for _ in range(7):
            sched.run_once()
            states_l.append(_mirror_state(store))
        store.flush_binds()
        binds_l = dict(store.binder.binds)
        store.close()

        assert binds_r == binds_l
        assert states_r == states_l
        assert "warm" in modes, f"child never went warm: {modes}"
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
