"""Resource arithmetic semantics tests.

Ports the *behavioral cases* of the reference's
``pkg/scheduler/api/resource_info_test.go`` (574 LoC): epsilon-tolerant
LessEqual, Sub assertions, IsEmpty quanta, FitDelta, Diff.
"""

import pytest

from volcano_tpu.api import (
    CPU,
    MEMORY,
    MIN_MEMORY,
    MIN_MILLI_CPU,
    Resource,
    res_min,
    share,
)


def R(cpu=0.0, mem=0.0, **scalars):
    return Resource(cpu, mem, scalars or None)


class TestLessEqual:
    def test_zero_fits_zero(self):
        assert R().less_equal(R())

    def test_within_cpu_epsilon(self):
        # |l - r| < 10 milli passes.
        assert R(cpu=1009, mem=0).less_equal(R(cpu=1000, mem=0))
        assert not R(cpu=1010, mem=0).less_equal(R(cpu=1000, mem=0))

    def test_within_memory_epsilon(self):
        m = 10 * 1024 * 1024
        assert R(mem=1000 + m - 1).less_equal(R(mem=1000))
        assert not R(mem=1000 + m).less_equal(R(mem=1000))

    def test_scalar_below_quantum_skipped(self):
        # Scalars requesting <= 10 milli always pass, even vs nothing.
        assert R(**{"nvidia.com/gpu": 10}).less_equal(R())
        assert not R(**{"nvidia.com/gpu": 1000}).less_equal(R())

    def test_scalar_epsilon(self):
        gpu = "nvidia.com/gpu"
        assert Resource(0, 0, {gpu: 1009}).less_equal(Resource(0, 0, {gpu: 1000}))
        assert not Resource(0, 0, {gpu: 1010}).less_equal(Resource(0, 0, {gpu: 1000}))

    def test_nil_scalars_pass(self):
        assert R(cpu=500, mem=100).less_equal(R(cpu=1000, mem=1000))


class TestLess:
    def test_strict(self):
        assert R(cpu=1, mem=1).less(R(cpu=2, mem=2))
        assert not R(cpu=2, mem=1).less(R(cpu=2, mem=2))

    def test_scalar_nil_receiver(self):
        # l has no scalars; r has a scalar above quantum -> less holds.
        assert R(cpu=1, mem=1).less(Resource(2, 2, {"x": 100}))
        # r scalar below quantum -> not less.
        assert not R(cpu=1, mem=1).less(Resource(2, 2, {"x": 5}))


class TestIsEmpty:
    def test_empty(self):
        assert R().is_empty()
        assert R(cpu=9.999).is_empty()
        assert R(mem=MIN_MEMORY - 1).is_empty()
        assert Resource(0, 0, {"g": 9}).is_empty()

    def test_not_empty(self):
        assert not R(cpu=MIN_MILLI_CPU).is_empty()
        assert not R(mem=MIN_MEMORY).is_empty()
        assert not Resource(0, 0, {"g": 10}).is_empty()


class TestArithmetic:
    def test_add(self):
        r = R(cpu=100, mem=200, g=300)
        r.add(R(cpu=10, mem=20, g=30))
        assert r.milli_cpu == 110 and r.memory == 220
        assert r.scalars["g"] == 330

    def test_sub_ok(self):
        r = R(cpu=100, mem=200, g=300)
        r.sub(R(cpu=50, mem=100, g=100))
        assert r.milli_cpu == 50 and r.memory == 100 and r.scalars["g"] == 200

    def test_sub_insufficient_asserts(self):
        with pytest.raises(AssertionError):
            R(cpu=10).sub(R(cpu=100))

    def test_sub_within_epsilon_allowed(self):
        # LessEqual's epsilon lets Sub go slightly negative: load-bearing.
        r = R(cpu=100)
        r.sub(R(cpu=109))
        assert r.milli_cpu == -9

    def test_multi(self):
        r = R(cpu=100, mem=200, g=50).multi(1.5)
        assert r.milli_cpu == 150 and r.memory == 300 and r.scalars["g"] == 75

    def test_fit_delta(self):
        r = R(cpu=100, mem=MIN_MEMORY * 3)
        r.fit_delta(R(cpu=50, mem=MIN_MEMORY))
        assert r.milli_cpu == 100 - 50 - MIN_MILLI_CPU
        assert r.memory == MIN_MEMORY * 3 - MIN_MEMORY - MIN_MEMORY

    def test_diff(self):
        inc, dec = R(cpu=100, mem=50).diff(R(cpu=40, mem=80))
        assert inc.milli_cpu == 60 and inc.memory == 0
        assert dec.milli_cpu == 0 and dec.memory == 30

    def test_set_max(self):
        r = R(cpu=10, mem=100)
        r.set_max_resource(R(cpu=5, mem=200, g=7))
        assert r.milli_cpu == 10 and r.memory == 200 and r.scalars["g"] == 7


class TestHelpers:
    def test_min(self):
        m = res_min(R(cpu=10, mem=50), R(cpu=20, mem=30))
        assert m.milli_cpu == 10 and m.memory == 30

    def test_share(self):
        assert share(0, 0) == 0.0
        assert share(5, 0) == 1.0
        assert share(5, 10) == 0.5


class TestParsing:
    def test_from_resource_list_strings(self):
        r = Resource.from_resource_list(
            {"cpu": "2", "memory": "1Gi", "pods": "110", "nvidia.com/gpu": "1"}
        )
        assert r.milli_cpu == 2000
        assert r.memory == 1024**3
        assert r.max_task_num == 110
        assert r.scalars["nvidia.com/gpu"] == 1000

    def test_from_resource_list_millis(self):
        r = Resource.from_resource_list({"cpu": "500m", "memory": "512Mi"})
        assert r.milli_cpu == 500
        assert r.memory == 512 * 1024**2
