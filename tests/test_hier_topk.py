"""Hierarchical block->shard->global top-k (the 100k x 1M scale tier).

The selection hierarchy (ops/wave.py ``_hier_blocks`` /
``_merge_block_cands`` / ``_topk_nodes``) must be PROVEN bit-identical
to the flat ``jax.lax.top_k`` path — binds and shortlist arrays —
including tie-heavy score planes (identical nodes rank by index) and
non-divisible shapes (which must fall back to the global form).  The
suite keeps shapes tiny: the hierarchy is forced through
``VOLCANO_TPU_TOPK_BLOCKS`` instead of node count, so the trace-static
decomposition is exercised without 100k-node compiles in tier-1.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import volcano_tpu.ops.wave as wave


def _ref_topk(scores, k):
    return np.asarray(
        jax.lax.top_k(jnp.asarray(scores), k)[1].astype(jnp.int32)
    )


@pytest.mark.parametrize("n,n_shards", [(256, 1), (256, 4), (250, 4),
                                        (256, 8)])
@pytest.mark.parametrize("blocks", [1, 8, 32])
def test_topk_nodes_exact_under_forced_blocks(monkeypatch, n, n_shards,
                                              blocks):
    """_topk_nodes == lax.top_k for every (shard, block) decomposition,
    on tie-heavy integer scores (ties resolve to the lower node id)."""
    monkeypatch.setenv("VOLCANO_TPU_TOPK_BLOCKS", str(blocks))
    rng = np.random.default_rng(n * 31 + n_shards * 7 + blocks)
    scores = rng.integers(0, 4, size=(5, n)).astype(np.float32)
    scores[1] = wave.NEG  # all-infeasible profile row
    scores[2] = 1.0  # one giant tie class
    for k in (1, 7, 64):
        got = np.asarray(wave._topk_nodes(jnp.asarray(scores), k,
                                          n_shards))
        assert np.array_equal(got, _ref_topk(scores, k)), (n, n_shards,
                                                           blocks, k)


def test_topk_nodes_exact_at_auto_hierarchy(monkeypatch):
    """The adaptive block stage (no env pin) engages past the node
    threshold and stays exact on a tie-heavy plane."""
    monkeypatch.delenv("VOLCANO_TPU_TOPK_BLOCKS", raising=False)
    monkeypatch.setenv("VOLCANO_TPU_TOPK_HIER_MIN", "1024")
    # The threshold constants are read at import; patch the module
    # values directly for the auto decision.
    monkeypatch.setattr(wave, "TOPK_HIER_MIN", 1024)
    monkeypatch.setattr(wave, "TOPK_BLOCK_ROWS", 256)
    n, k = 4096, 32
    assert wave._hier_blocks(n, k, 1) > 1  # the stage actually engages
    rng = np.random.default_rng(0)
    scores = rng.integers(0, 3, size=(4, n)).astype(np.float32)
    got = np.asarray(wave._topk_nodes(jnp.asarray(scores), k, 1))
    assert np.array_equal(got, _ref_topk(scores, k))


def test_hier_blocks_decomposition_rules():
    """Shape rules of the trace-static decomposition: pow2, divides N,
    multiple of the shard count, global fallback when nothing fits."""
    # Pinned counts clamp to a divisor >= the shard count.
    import os

    os.environ["VOLCANO_TPU_TOPK_BLOCKS"] = "48"
    try:
        nb = wave._hier_blocks(256, 8, 4)
        assert nb in (4, 8, 16, 32) and 256 % nb == 0 and nb % 4 == 0
        # Non-divisible node axes fall back to the global form.
        assert wave._hier_blocks(250, 8, 4) == 1
    finally:
        del os.environ["VOLCANO_TPU_TOPK_BLOCKS"]
    # Default: small planes keep the historic two-stage (shards) form.
    assert wave._hier_blocks(2048, 64, 1) == 1
    assert wave._hier_blocks(2048, 64, 4) == 4


def test_merge_block_cands_shard_aware_equals_flat():
    """The shard->global merge tail is bit-identical to one flat reduce
    over the same block candidates (the communication restructuring
    must not change the selected set or its order)."""
    rng = np.random.default_rng(7)
    U, B, k = 3, 8, 24
    nlb = 64
    scores = rng.integers(0, 4, size=(U, B, nlb)).astype(np.float32)
    # klb = min(k, nlb): the retention every production caller uses —
    # a block can contribute at most min(k, nlb) global winners, so the
    # merged set equals the direct top-k.  (Under-retaining blocks is a
    # different selection; the flat-vs-sharded agreement below is
    # asserted for that case separately.)
    for klb in (min(k, nlb), 8):
        loc_s, loc_i = jax.lax.top_k(jnp.asarray(scores), klb)
        gid = loc_i.astype(jnp.int32) + (
            jnp.arange(B, dtype=jnp.int32) * nlb)[None, :, None]
        flat = np.asarray(wave._merge_block_cands(loc_s, gid, k, 1))
        for n_shards in (2, 4, 8):
            sharded = np.asarray(
                wave._merge_block_cands(loc_s, gid, k, n_shards))
            assert np.array_equal(flat, sharded), (klb, n_shards)
        if klb == min(k, nlb):
            # Full retention: the merge IS the direct top-k.
            ref = _ref_topk(scores.reshape(U, B * nlb), k)
            assert np.array_equal(flat, ref)


def test_coarse_shortlist_bit_identical_across_hierarchy(monkeypatch):
    """Shortlist ARRAYS from the seeded snapshot are bit-identical with
    the hierarchy forced on vs off (the acceptance proof at snapshot
    granularity; solve-level parity rides the existing twophase/mesh
    suites)."""
    from volcano_tpu.synth import synthetic_cluster, solve_args_from_store
    from volcano_tpu.ops.wave import solve_wave

    def run():
        store = synthetic_cluster(n_nodes=96, n_pods=512, gang_size=4,
                                  zones=4, affinity_fraction=0.2,
                                  anti_affinity_fraction=0.2, seed=11)
        args, _ = solve_args_from_store(store)
        res = solve_wave(*args, wave=128)
        return jax.device_get(
            (res.assigned, res.pipelined, res.never_ready,
             res.fit_failed))

    monkeypatch.setenv("VOLCANO_TPU_TOPK_BLOCKS", "1")
    base = run()
    monkeypatch.setenv("VOLCANO_TPU_TOPK_BLOCKS", "8")
    hier = run()
    for b, h in zip(base, hier):
        assert np.array_equal(np.asarray(b), np.asarray(h))


def test_warm_shortlist_merge_shard_parity():
    """_warm_shortlist's hierarchical merge (mesh_shards > 1) returns
    the same shortlist as the flat merge on identical candidates —
    exercised through DeviceIncremental so the devincr warm path and
    the kernel agree on the block geometry."""
    from volcano_tpu.ops import devincr as dvm

    # Direct kernel-level check on synthetic candidates mirrors
    # test_merge_block_cands; here assert the devincr block geometry
    # stays a multiple of the shard count as N scales.
    for n, n_sh in [(2048, 4), (1 << 17, 8)]:
        B = max(dvm.warm_blocks(), n_sh)
        max_rows = dvm.warm_block_rows()
        while n % (B * 2) == 0 and n // B > max_rows:
            B *= 2
        assert B % n_sh == 0 and n % B == 0
        assert n // B <= max(max_rows, n // max(dvm.warm_blocks(), n_sh))
