"""Prometheus exposition correctness (ISSUE 3 satellites).

- Golden-text: ``expose_text`` emits exactly the expected series lines
  for gauge / counter / histogram with labels (pinning the text format
  across the bounded-histogram rewrite — bucket counts + sum + count
  replaced the unbounded per-observation list).
- Memory bound: a histogram's per-label state stays fixed-size no
  matter how many observations land.
- Concurrency: scrapes racing writers must never throw ("dictionary
  changed size during iteration") nor tear a histogram's bucket/count
  invariants.

Tier-1, CPU-only: nothing here touches jax.
"""

import threading

import pytest

from volcano_tpu.metrics.metrics import _DEFAULT_BUCKETS, Metrics

pytestmark = pytest.mark.tier1


def _series_lines(text, name):
    return [l for l in text.splitlines()
            if l.startswith(name) and not l.startswith("#")]


# ---------------------------------------------------------------- golden


def test_expose_text_golden_gauge_counter_histogram():
    m = Metrics()
    m.queue_share.set(0.25, queue="q1")
    m.queue_share.set(0.75, queue="q2")
    m.schedule_attempts.inc(result="ok")
    m.schedule_attempts.inc(2.0, result="err")
    m.device_solve_latency.observe(0.004)   # first bucket
    m.device_solve_latency.observe(3.0)     # le=5 bucket
    m.device_solve_latency.observe(50000.0)  # beyond every bucket
    text = m.expose_text()

    assert _series_lines(text, "volcano_queue_share") == [
        'volcano_queue_share{queue="q1"} 0.25',
        'volcano_queue_share{queue="q2"} 0.75',
    ]
    assert _series_lines(text, "volcano_schedule_attempts_total") == [
        'volcano_schedule_attempts_total{result="ok"} 1.0',
        'volcano_schedule_attempts_total{result="err"} 2.0',
    ]
    hist = "volcano_device_solve_latency_milliseconds"
    expected = []
    for b in _DEFAULT_BUCKETS:
        cnt = sum(1 for v in (0.004, 3.0, 50000.0) if v <= b)
        expected.append(f'{hist}_bucket{{le="{b}"}} {cnt}')
    expected.append(f'{hist}_bucket{{le="+Inf"}} 3')
    expected.append(f'{hist}_sum{{}} 50003.004')
    expected.append(f'{hist}_count{{}} 3')
    assert _series_lines(text, hist) == expected
    # HELP/TYPE headers precede every family.
    assert f"# HELP {hist} " in text
    assert f"# TYPE {hist} histogram" in text


def test_histogram_state_is_bounded():
    m = Metrics()
    h = m.e2e_scheduling_latency
    for i in range(10_000):
        h.observe(float(i % 977))
    (state,) = h.data.values()
    counts, total, n = state
    # Fixed-size state: one slot per bucket + overflow, no raw list.
    assert len(counts) == len(_DEFAULT_BUCKETS) + 1
    assert n == 10_000
    assert sum(counts) == 10_000
    assert total == sum(float(i % 977) for i in range(10_000))


# ----------------------------------------------------------- concurrency


def test_concurrent_scrape_while_observing_never_throws():
    """Writers mutate label dicts while a scraper iterates: without the
    shared registry lock this raced into RuntimeError (dict changed
    size during iteration) and torn histogram reads."""
    m = Metrics()
    stop = threading.Event()
    errors = []

    def writer(tid):
        i = 0
        try:
            while not stop.is_set():
                m.e2e_scheduling_latency.observe(
                    float(i % 100), worker=f"w{tid}-{i % 50}")
                m.schedule_attempts.inc(result=f"r{tid}-{i % 50}")
                m.unschedule_task_count.set(i, job_name=f"j{tid}-{i % 50}")
                i += 1
        except Exception as err:  # pragma: no cover - the failure mode
            errors.append(err)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(3)]
    for t in threads:
        t.start()
    try:
        for _ in range(200):
            text = m.expose_text()
            # Scrape-consistency invariant: within one scrape, every
            # histogram's +Inf bucket equals its count line.
            lines = text.splitlines()
            for i, line in enumerate(lines):
                if '_bucket{' in line and 'le="+Inf"' in line:
                    inf_v = line.rsplit(" ", 1)[1]
                    cnt_line = lines[i + 2]
                    assert cnt_line.rsplit(" ", 1)[1] == inf_v
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not errors, errors


def test_batch_and_single_updates_serialize_with_scrapes():
    m = Metrics()
    keys = [(("job_name", f"j{i}"),) for i in range(100)]
    stop = threading.Event()
    errors = []

    def batcher():
        try:
            while not stop.is_set():
                m.job_retry_counts.inc_many(keys)
                m.unschedule_task_count.set_many(
                    (k, 1.0) for k in keys)
        except Exception as err:  # pragma: no cover
            errors.append(err)

    t = threading.Thread(target=batcher)
    t.start()
    try:
        for _ in range(200):
            m.expose_text()
    finally:
        stop.set()
        t.join(timeout=30)
    assert not errors, errors
    total = sum(m.job_retry_counts.data.values())
    assert total % len(keys) == 0  # whole batches only, never torn