"""Pod-journey tracing (ISSUE 18): per-pod scheduling timelines.

Pins the acceptance contracts of obs/journey.py and its capture seams:

- a churned pipelined store yields complete, conserved journeys —
  ``conservation_check`` over every bound pod returns nothing;
- cross-shard steal and conflict stitch into one timeline with the
  correct shard attribution (the thief's shard id on the stolen
  queue's binds; ``cross-shard-conflict`` drops carry the voiding
  shard and the ownership handoff epoch);
- the why-pending verdict compresses the recent drop chain into one
  operator sentence (``capacity-taken x2 on shard 1, ...``);
- Perfetto export emits parseable async journey tracks (``ph`` b/n/e);
- the event ring is bounded (overwrite-oldest, drop counter moves);
- the kill switch (``VOLCANO_TPU_JOURNEY=0``) leaves the store with no
  journey attached, so hot paths pay one attribute load;
- flight records carry their shard id under a sharded scheduler, and
  ``/debug/pods/<uid>`` serves the stitched timeline without the
  store lock.

All CPU-only (conftest pins JAX_PLATFORMS=cpu); tier-1.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    Queue,
    TaskStatus,
)
from volcano_tpu.cache import ClusterStore
from volcano_tpu.metrics import metrics
from volcano_tpu.obs import JourneyLog, export
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.shard import ShardedScheduler, stable_shard
from volcano_tpu.synth import synthetic_cluster

pytestmark = pytest.mark.tier1

ST_BOUND = int(TaskStatus.Bound)
ST_PENDING = int(TaskStatus.Pending)

BOUND_MASK = (int(TaskStatus.Allocated) | int(TaskStatus.Binding)
              | int(TaskStatus.Bound) | int(TaskStatus.Running)
              | int(TaskStatus.Succeeded))


def _qname(shard, n_shards=2, avoid=()):
    i = 0
    while True:
        name = f"q{i}"
        if name not in avoid and stable_shard(name, n_shards) == shard:
            return name
        i += 1


def _add_gang(store, queue, name, pods, cpu="1"):
    store.add_pod_group(PodGroup(name=name, min_member=pods, queue=queue))
    for k in range(pods):
        store.add_pod(Pod(
            name=f"{name}-{k}",
            annotations={GROUP_NAME_ANNOTATION: name},
            containers=[{"cpu": cpu, "memory": "1Gi"}],
        ))


def _churn_store(n_nodes=16, n_pods=64, frac=3):
    store = synthetic_cluster(n_nodes=n_nodes, n_pods=n_pods,
                              gang_size=4, seed=3)
    store.pipeline = True

    def feed(fc):
        m = fc.m
        rows = np.flatnonzero(
            (m.p_status[:fc.Pn] == ST_BOUND) & m.p_alive[:fc.Pn]
        )
        if len(rows):
            fc._unbind_rows(rows[:max(1, len(rows) // frac)])

    store.cycle_feed = feed
    return store


def _bound_uids(store):
    with store._lock:
        m = store.mirror
        return [m.p_uid[i] for i in range(len(m.p_uid))
                if m.p_alive[i] and m.p_uid[i]
                and int(m.p_status[i]) & BOUND_MASK]


# ------------------------------------------------------- conservation


def test_churned_store_yields_complete_conserved_journeys():
    """Sustained re-pend churn over a pipelined store: every pod the
    mirror says is bound has a complete, orphan-free journey — the
    endurance gate's invariant, checked directly."""
    store = _churn_store()
    assert store.journey is not None
    sched = Scheduler(store)
    for _ in range(8):
        sched.run_once()
    store.flush_binds()

    bound = _bound_uids(store)
    assert bound, "churn never bound a pod"
    assert store.journey.conservation_check(bound) == []

    st = store.journey.stats()
    assert st["events"] > 0
    assert st["bound"] >= len(bound)
    assert st["ttb_p50_ms"] is not None
    assert st["ttb_p99_ms"] >= st["ttb_p50_ms"]
    # Steady-state repeats folded into bulk counters, not per-pod rows:
    # the re-pend loop re-binds the same backlog every cycle.
    assert st["rebinds"] > 0

    # One bound pod's timeline: rooted, monotone, bind latency filled.
    tl = store.journey.timeline(bound[0])
    assert tl is not None
    assert tl["events"][0]["kind"] == "enqueued"
    kinds = [e["kind"] for e in tl["events"]]
    assert "bound" in kinds
    assert tl["monotone"] is True
    assert tl["time_to_bind_ms"] is not None
    assert tl["why_pending"] == "bound"
    # Gang time-to-full-bind observed for fully-bound gangs.
    assert st["gang_ttfb_p50_ms"] is not None
    store.close()


def test_conservation_check_flags_orphans_and_incomplete():
    jr = JourneyLog(capacity=256)
    jr.pod_event("u-root", "enqueued", status=ST_PENDING, queue="q")
    anoms = jr.conservation_check(["u-root", "u-ghost"])
    by_reason = {a.reason: a.detail for a in anoms}
    assert by_reason["journey-orphan"]["uids"] == ["u-ghost"]
    assert by_reason["journey-incomplete"]["uids"] == ["u-root"]
    jr.pod_event("u-root", "bound")
    assert jr.conservation_check(["u-root"]) == []
    # Synthetic adoption (pod_resync after a detach window) is a
    # complete root: the adoption is the recorded provenance.
    jr.pod_resync([("u-adopted", ST_BOUND)])
    assert jr.conservation_check(["u-adopted"]) == []


# -------------------------------------------------------- cross-shard


def test_cross_shard_conflict_stitches_with_shard_attribution():
    """The same-node race (test_shards idiom): both shards solve the
    same cap-1 nodes in one overlap; the loser's journey records the
    ``cross-shard-conflict`` drop with the voiding shard + handoff
    epoch, then the re-place's ``bound`` — one stitched timeline."""
    qa = _qname(0)
    qb = _qname(1)
    store = ClusterStore()
    for i in range(2):
        store.add_node(Node(
            name=f"n{i}",
            allocatable={"cpu": "1", "memory": "8Gi", "pods": 8},
        ))
    store.add_queue(Queue(name=qa, weight=1))
    store.add_queue(Queue(name=qb, weight=1))
    _add_gang(store, qa, "ga", pods=1)
    _add_gang(store, qb, "gb", pods=1)
    store.pipeline = True

    sched = ShardedScheduler(store, shards=2)
    for _ in range(6):
        sched.run_once()
    store.flush_binds()

    rows = store.journey.trace_rows()
    conflicts = [r for r in rows if r["kind"] == "dropped"
                 and r.get("detail") == "cross-shard-conflict"]
    assert conflicts, "the race never recorded a cross-shard void"
    for r in conflicts:
        assert r.get("shard") in (0, 1)
        assert r.get("handoff_epoch", -1) >= 0

    # The loser's stitched timeline: conflict drop AND eventual bind.
    loser = conflicts[0]["uid"]
    tl = store.journey.timeline(loser)
    kinds = [e["kind"] for e in tl["events"]]
    assert "dropped" in kinds and "bound" in kinds
    assert tl["why_pending"] == "bound"
    # Dispatched/bound events carry real shard ids under sharding.
    shards_seen = {e["shard"] for e in tl["events"] if "shard" in e}
    assert shards_seen & {0, 1}
    assert store.journey.conservation_check(_bound_uids(store)) == []
    store.close()


def test_stolen_queue_binds_attributed_to_thief_shard():
    """Work stealing: shard 1 steals a queue based on shard 0 and
    binds it — the journey's bound events must carry the THIEF's shard
    id (the capture rides the executing FastCycle, not the hash)."""
    qx = _qname(0)
    qy = _qname(0, avoid={qx})
    store = ClusterStore()
    for i in range(2):
        store.add_node(Node(
            name=f"n{i}",
            allocatable={"cpu": "8", "memory": "32Gi", "pods": 64},
        ))
    store.add_queue(Queue(name=qx, weight=1))
    store.add_queue(Queue(name=qy, weight=1))
    _add_gang(store, qx, "big", pods=4)
    _add_gang(store, qy, "small", pods=2)

    sched = ShardedScheduler(store, shards=2)
    thief = sched.schedulers[1]
    thief.run_once()
    thief.run_once()
    store.flush_binds()

    with store._lock:
        stolen = [p.uid for p in store.pods.values()
                  if p.name.startswith("big-")]
    for uid in stolen:
        tl = store.journey.timeline(uid)
        bound_evs = [e for e in tl["events"] if e["kind"] == "bound"]
        assert bound_evs and all(e["shard"] == 1 for e in bound_evs)
    store.close()


# -------------------------------------------------------- why-pending


def test_why_pending_verdict_for_capacity_starved_gang():
    """Capacity theft (test_obs idiom): thieves bind both cap-1 nodes
    mid-overlap, the gang's rows are voided as ``capacity-taken`` —
    why-pending compresses the drop chain into the operator sentence."""
    store = ClusterStore()
    for i in range(2):
        store.add_node(Node(
            name=f"n{i}",
            allocatable={"cpu": "1", "memory": "8Gi", "pods": 64},
        ))
    store.add_pod_group(PodGroup(name="g", min_member=1))
    for k in range(2):
        store.add_pod(Pod(
            name=f"p{k}",
            annotations={GROUP_NAME_ANNOTATION: "g"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
        ))
    store.pipeline = True
    sched = Scheduler(store)
    sched.run_once()  # dispatch: p0 -> one node, p1 -> the other
    for i in range(2):
        store.add_pod(Pod(
            name=f"thief{i}",
            annotations={GROUP_NAME_ANNOTATION: "g"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
            node_name=f"n{i}",
        ))
    sched.run_once()  # guard voids both rows as capacity-taken

    with store._lock:
        starved = [p.uid for p in store.pods.values()
                   if p.name.startswith("p") and not p.node_name]
    assert starved, "theft did not starve the gang"
    verdict = store.journey.why_pending(starved[0])
    assert verdict.startswith("capacity-taken"), verdict
    tl = store.journey.timeline(starved[0])
    assert tl["why_pending"] == verdict
    assert tl["time_to_bind_ms"] is None
    store.close()


def test_why_pending_compresses_drop_chain():
    jr = JourneyLog(capacity=256)
    jr.pod_event("u1", "enqueued", status=ST_PENDING, queue="q")
    jr.pod_event("u1", "dispatched", shard=1, solve_id=7)
    for _ in range(4):
        jr.pod_event("u1", "dropped", shard=1, detail="capacity-taken")
    jr.pod_event("u1", "dropped", shard=0,
                 detail="cross-shard-conflict", epoch=3)
    assert jr.why_pending("u1") == (
        "capacity-taken x4 on shard 1, cross-shard-conflict on shard 0")
    assert jr.why_pending("nobody") == "unknown (no journey state)"
    # Pre-dispatch and post-dispatch-no-drop verdicts.
    jr.pod_event("u2", "enqueued", status=ST_PENDING)
    assert jr.why_pending("u2") == "never considered (queue backlog)"
    jr.pod_event("u2", "dispatched")
    assert jr.why_pending("u2") == \
        "considered, no drops recorded (awaiting commit)"
    jr.pod_event("u3", "enqueued", status=ST_PENDING)
    jr.pod_event("u3", "evicted")
    assert jr.why_pending("u3") == "evicted (awaiting restore)"


# ----------------------------------------------------------- perfetto


def test_perfetto_export_emits_async_journey_tracks():
    store = _churn_store(n_nodes=8, n_pods=32)
    sched = Scheduler(store)
    for _ in range(4):
        sched.run_once()
    store.flush_binds()

    trace = export.perfetto_trace(store.flight.recent(),
                                  journey=store.journey.trace_rows())
    parsed = json.loads(json.dumps(trace))  # Chrome JSON round-trip
    evs = parsed["traceEvents"]
    jevs = [e for e in evs if e.get("cat") == "journey"]
    assert {e["ph"] for e in jevs} == {"b", "n", "e"}
    # Every async track is bracketed: b/e pairs per pod id.
    by_id = {}
    for e in jevs:
        by_id.setdefault(e["id"], []).append(e["ph"])
    for phases in by_id.values():
        assert phases[0] == "b" and phases[-1] == "e"
    # The journey rides its own named track.
    names = {m["args"]["name"] for m in evs
             if m.get("ph") == "M" and m["name"] == "thread_name"}
    assert "journey" in names
    # A solve-id-carrying journey instant joined a flow: some flow
    # phase shares a ts with a journey instant on the journey track.
    jtid = {e["tid"] for e in jevs}.pop()
    assert any(e.get("cat") == "flow" and e["tid"] == jtid
               for e in evs)
    store.close()


# ------------------------------------------------- bounded ring + kill


def test_ring_is_bounded_and_overwrites_oldest():
    jr = JourneyLog(capacity=8)
    for k in range(20):
        jr.pod_event(f"u{k}", "enqueued", status=ST_PENDING)
    rows = jr.trace_rows()
    assert len(rows) == 8
    assert [r["uid"] for r in rows] == [f"u{k}" for k in range(12, 20)]
    st = jr.stats()
    assert st["events"] == 20
    assert st["events_dropped"] == 12
    # Summaries survive ring eviction: the uid-keyed state is intact.
    assert st["pods"] == 20
    assert jr.timeline("u0")["events"] == []  # ring evicted, state kept


def test_kill_switch_detaches_journey(monkeypatch):
    monkeypatch.setenv("VOLCANO_TPU_JOURNEY", "0")
    store = _churn_store(n_nodes=4, n_pods=16)
    assert store.journey is None
    assert store.mirror.journey is None
    before = dict(metrics.journey_events.data)
    sched = Scheduler(store)
    for _ in range(3):
        sched.run_once()
    store.flush_binds()
    # Hot paths saw the None handle and recorded nothing.
    assert dict(metrics.journey_events.data) == before
    assert _bound_uids(store), "kill switch must not affect scheduling"
    store.close()


# ----------------------------------------------------- debug endpoint


def test_debug_pods_endpoint_serves_timeline_without_store_lock():
    from volcano_tpu.service import Service

    store = _churn_store(n_nodes=8, n_pods=32)
    sched = Scheduler(store)
    for _ in range(4):
        sched.run_once()
    store.flush_binds()
    uid = _bound_uids(store)[0]

    svc = Service(store=store, schedule_period=30.0,
                  controller_period=5.0)
    port = svc.start(http_port=0)
    try:
        def get(path):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{path}",
                        timeout=10) as r:
                    return json.loads(r.read()), r.status
            except urllib.error.HTTPError as err:
                return json.loads(err.read()), err.code

        # Serve WITH the store lock held elsewhere: must not block
        # (the journey has its own lock, never nested inside store
        # work on the read side).
        result = {}
        with store._lock:
            t = threading.Thread(target=lambda: result.update(
                get(f"/debug/pods/{uid}")[0]))
            t.start()
            t.join(timeout=5)
            assert not t.is_alive(), "/debug/pods blocked on store lock"
        assert result["uid"] == uid
        assert result["why_pending"] == "bound"
        assert result["events"][0]["kind"] == "enqueued"

        body, _status = get("/debug/pods/does-not-exist")
        assert "error" in body

        health, _status = get("/debug/health")
        roll = health["journey"]
        assert roll["pods_tracked"] > 0
        assert any(q["bound_total"] > 0 for q in roll["queues"].values())
    finally:
        svc.stop()
        store.close()


# -------------------------------------------- flight-record shard tag


def test_flight_records_tagged_with_shard_id():
    """/debug/cycles aggregates ALL shards' records (the recorder is
    store-wide); each record carries the executing shard's id so the
    merged stream stays attributable."""
    store = synthetic_cluster(n_nodes=8, n_pods=32, gang_size=4,
                              n_queues=4, seed=11)
    store.pipeline = True
    sched = ShardedScheduler(store, shards=2)
    for _ in range(3):
        sched.run_once()
    store.flush_binds()
    recs = store.flight.recent()
    assert {r.shard for r in recs} == {0, 1}
    assert all(r.to_dict()["shard"] in (0, 1) for r in recs)
    store.close()

    # Unsharded records keep shard=None (the kill-switch shape).
    single = _churn_store(n_nodes=4, n_pods=16)
    Scheduler(single).run_once()
    assert all(r.shard is None for r in single.flight.recent())
    single.close()
