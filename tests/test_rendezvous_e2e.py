"""Distributed-workload rendezvous proven end-to-end: a 2-replica gang
is submitted through the controller plane, scheduled and bound, and the
bound pods' env — rendered by the svc/env job plugins
(VC_COORDINATOR_ADDRESS / VC_PROCESS_ID / VC_PROCESS_COUNT, the
hostfile/env analog of svc.go:306-340) — is handed to two REAL OS
processes that complete a ``jax.distributed.initialize`` handshake.

This is the rebuild's test/e2e/mpi.go:27 moment: the reference runs an
actual MPI hello-world to completion on kind; here the test plays the
kubelet and the workers rendezvous through JAX's coordination service.
"""

import json
import os
import socket
import subprocess
import sys

from volcano_tpu.cache import ClusterStore
from volcano_tpu.api import Node
from volcano_tpu.controllers import ControllerManager
from volcano_tpu.controllers.apis import Job, TaskSpec
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.sim import ClusterSimulator

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_gang_rendezvous_two_real_processes():
    store = ClusterStore()
    for i in range(2):
        store.add_node(Node(name=f"n{i}",
                            allocatable={"cpu": "8", "memory": "16Gi",
                                         "pods": 110}))
    cm = ControllerManager(store)
    sched = Scheduler(store)
    sim = ClusterSimulator(store)

    job = Job(
        name="jaxdist",
        min_available=2,
        tasks=[TaskSpec(name="worker", replicas=2,
                        containers=[{"cpu": "1", "memory": "1Gi"}])],
        plugins={"svc": [], "env": []},
    )
    store.add_batch_job(job)
    for _ in range(4):
        cm.process()
        sched.run_once()
        sim.step()
        cm.process()

    pods = [p for p in store.pods.values()
            if p.owner_job == "default/jaxdist"]
    assert len(pods) == 2
    assert all(p.node_name for p in pods), "gang not fully bound"

    # The coordinator port from the rendered env is a fixed cluster port;
    # rebind it to a free local port for the single-host run (the test is
    # the kubelet AND the cluster DNS here).
    port = _free_port()
    procs = []
    try:
        for pod in sorted(pods, key=lambda p: int(p.env["VC_PROCESS_ID"])):
            env = dict(os.environ)
            env.update({k: str(v) for k, v in pod.env.items()})
            host, _, _ = env["VC_COORDINATOR_ADDRESS"].rpartition(":")
            env["VC_COORDINATOR_ADDRESS"] = f"{host}:{port}"
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("XLA_FLAGS", None)  # one local device per worker
            env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
            procs.append(subprocess.Popen(
                [sys.executable, os.path.join(REPO, "tests",
                                              "rendezvous_worker.py")],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                env=env, text=True,
            ))
        results = []
        for p in procs:
            out, err = p.communicate(timeout=180)
            assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
            results.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    # Both processes completed the handshake and saw the whole world.
    assert sorted(r["process_id"] for r in results) == [0, 1]
    assert all(r["process_count"] == 2 for r in results)
    assert all(r["global_devices"] == 2 for r in results)
    assert all(r["local_devices"] == 1 for r in results)
    store.close()
