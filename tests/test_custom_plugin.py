"""Custom (out-of-tree) plugin through the public registry: the
RegisterPluginBuilder extension point (framework/plugins.go analog).

A configuration naming a non-built-in plugin is ineligible for the fast
path, so the cycle runs on the object-session path with the custom
callbacks dispatched through the tiered session machinery.
"""

import numpy as np

from volcano_tpu.api import GROUP_NAME_ANNOTATION, Node, Pod, PodGroup
from volcano_tpu.cache import ClusterStore
from volcano_tpu.framework import register_plugin_builder
from volcano_tpu.scheduler import Scheduler

CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: gang
  - name: pinned-nodes
- plugins:
  - name: binpack
"""


class PinnedNodesPlugin:
    """Only nodes whose name appears in the plugin argument pass the
    predicate — a minimal custom policy."""

    def __init__(self, arguments):
        allow = ""
        for arg in arguments or []:
            if str(arg).startswith("--allow="):
                allow = str(arg).split("=", 1)[1]
        self.allowed = set(a for a in allow.split(",") if a)
        self.opened = False

    @property
    def name(self):
        return "pinned-nodes"

    def on_session_open(self, ssn):
        self.opened = True

        def predicate(task, node):
            if node.name not in self.allowed:
                raise RuntimeError(f"node {node.name} not pinned")

        ssn.add_predicate_fn(self.name, predicate)

    def on_session_close(self, ssn):
        pass


def test_custom_plugin_via_registry():
    instances = []

    def builder(arguments):
        p = PinnedNodesPlugin(["--allow=n1"])
        instances.append(p)
        return p

    register_plugin_builder("pinned-nodes", builder)
    store = ClusterStore()
    for i in range(3):
        store.add_node(Node(name=f"n{i}",
                            allocatable={"cpu": "8", "memory": "16Gi"}))
    store.add_pod_group(PodGroup(name="g", min_member=2))
    for k in range(2):
        store.add_pod(Pod(name=f"p-{k}",
                          containers=[{"cpu": "1", "memory": "1Gi"}],
                          annotations={GROUP_NAME_ANNOTATION: "g"}))
    Scheduler(store, conf_str=CONF).run_once()
    assert instances and instances[0].opened
    assert len(store.binder.binds) == 2
    assert set(store.binder.binds.values()) == {"n1"}, (
        f"custom predicate ignored: {store.binder.binds}"
    )


class DeviceMaskPlugin:
    """TPU-native custom plugin: contributes a [P, N] mask factory
    (ssn.add_device_mask_fn) instead of a per-pair host callback."""

    def __init__(self, allowed):
        self.allowed = allowed

    @property
    def name(self):
        return "device-mask"

    def on_session_open(self, ssn):
        def mask(cluster, pending, node_names):
            m = np.zeros((len(pending), len(node_names)), bool)
            for j, nm in enumerate(node_names):
                if nm in self.allowed:
                    m[:, j] = True
            return m

        ssn.add_device_mask_fn(self.name, mask)

    def on_session_close(self, ssn):
        pass


CONF_MASK = CONF.replace("pinned-nodes", "device-mask")


def test_device_mask_fn_via_registry():
    register_plugin_builder("device-mask",
                            lambda args: DeviceMaskPlugin({"n2"}))
    store = ClusterStore()
    for i in range(3):
        store.add_node(Node(name=f"n{i}",
                            allocatable={"cpu": "8", "memory": "16Gi"}))
    store.add_pod_group(PodGroup(name="g", min_member=2))
    for k in range(2):
        store.add_pod(Pod(name=f"p-{k}",
                          containers=[{"cpu": "1", "memory": "1Gi"}],
                          annotations={GROUP_NAME_ANNOTATION: "g"}))
    Scheduler(store, conf_str=CONF_MASK).run_once()
    assert len(store.binder.binds) == 2
    assert set(store.binder.binds.values()) == {"n2"}


def test_custom_plugin_with_sequential_solver():
    register_plugin_builder("pinned-nodes",
                            lambda args: PinnedNodesPlugin(["--allow=n1"]))
    conf = CONF + """configurations:
- name: allocate
  arguments:
    solver: seq
"""
    store = ClusterStore()
    for i in range(3):
        store.add_node(Node(name=f"n{i}",
                            allocatable={"cpu": "8", "memory": "16Gi"}))
    store.add_pod_group(PodGroup(name="g", min_member=2))
    for k in range(2):
        store.add_pod(Pod(name=f"p-{k}",
                          containers=[{"cpu": "1", "memory": "1Gi"}],
                          annotations={GROUP_NAME_ANNOTATION: "g"}))
    Scheduler(store, conf_str=conf).run_once()
    assert len(store.binder.binds) == 2
    assert set(store.binder.binds.values()) == {"n1"}


class SteerScorePlugin:
    """Custom scorer: strongly prefers one node via add_node_order_fn."""

    def __init__(self, target, weight=1000.0):
        self.target = target
        self.weight = weight

    @property
    def name(self):
        return "steer-score"

    def on_session_open(self, ssn):
        def score(task, node):
            return self.weight if node.name == self.target else 0.0

        ssn.add_node_order_fn(self.name, score)

    def on_session_close(self, ssn):
        pass


def test_custom_node_order_fn_steers_placement():
    register_plugin_builder("steer-score",
                            lambda args: SteerScorePlugin("n2"))
    conf = CONF.replace("pinned-nodes", "steer-score")
    store = ClusterStore()
    for i in range(3):
        store.add_node(Node(name=f"n{i}",
                            allocatable={"cpu": "8", "memory": "16Gi"}))
    store.add_pod_group(PodGroup(name="g", min_member=2))
    for k in range(2):
        store.add_pod(Pod(name=f"p-{k}",
                          containers=[{"cpu": "1", "memory": "1Gi"}],
                          annotations={GROUP_NAME_ANNOTATION: "g"}))
    Scheduler(store, conf_str=conf).run_once()
    assert len(store.binder.binds) == 2
    assert set(store.binder.binds.values()) == {"n2"}, (
        f"custom scorer ignored: {store.binder.binds}"
    )
