"""Preempt and reclaim action tests.

Mirrors the reference's preempt tests
(pkg/scheduler/actions/preempt/preempt_test.go): a running low-priority job
occupies the cluster; a higher-priority pending job triggers eviction of
victims and pipelines its tasks.  Reclaim: cross-queue eviction for a
starved queue (test/e2e queue.go behavior).
"""

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    PodGroupPhase,
    PodPhase,
    PriorityClass,
    Queue,
    TaskStatus,
)
from volcano_tpu.cache import ClusterStore, FakeBinder, FakeEvictor
from volcano_tpu.scheduler import Scheduler

PREEMPT_CONF = """
actions: "enqueue, allocate, preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""

RECLAIM_CONF = """
actions: "enqueue, reclaim"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def running_pod(name, group, cpu, node, ns="default", priority=None):
    return Pod(
        name=name,
        namespace=ns,
        annotations={GROUP_NAME_ANNOTATION: group},
        containers=[{"cpu": cpu, "memory": "1Gi"}],
        phase=PodPhase.Running,
        node_name=node,
        priority=priority,
    )


def pending_pod(name, group, cpu, ns="default", priority=None):
    return Pod(
        name=name,
        namespace=ns,
        annotations={GROUP_NAME_ANNOTATION: group},
        containers=[{"cpu": cpu, "memory": "1Gi"}],
        priority=priority,
    )


def test_preempt_evicts_lower_priority_victims():
    evictor = FakeEvictor()
    store = ClusterStore(evictor=evictor)
    store.add_node(Node(name="n1", allocatable={"cpu": "4", "memory": "8Gi",
                                                "pods": 110}))
    store.add_priority_class(PriorityClass(name="high", value=100))
    store.add_priority_class(PriorityClass(name="low", value=1))

    store.add_pod_group(PodGroup(name="lo", min_member=1,
                                 priority_class="low"))
    store.pod_groups["default/lo"].status.phase = PodGroupPhase.Running.value
    store.add_pod(running_pod("lo-0", "lo", "2", "n1", priority=1))
    store.add_pod(running_pod("lo-1", "lo", "2", "n1", priority=1))

    store.add_pod_group(PodGroup(name="hi", min_member=1,
                                 priority_class="high"))
    store.pod_groups["default/hi"].status.phase = PodGroupPhase.Inqueue.value
    store.add_pod(pending_pod("hi-0", "hi", "2", priority=100))

    Scheduler(store, conf_str=PREEMPT_CONF).run_once()

    # A low-priority victim was evicted to make room.
    assert len(evictor.evicts) >= 1
    assert all(e.startswith("default/lo-") for e in evictor.evicts)
    # The preemptor is pipelined onto the node in the store's view of the
    # next cycle (the evicted pod is releasing; hi-0 stays pending until
    # resources free, which is correct pipelining semantics).


def test_preempt_respects_gang_min_available():
    # Victim job has min_member=2 with exactly 2 running tasks: gang
    # protection allows evicting at most... 2-1 < 2 -> no victims at all.
    evictor = FakeEvictor()
    store = ClusterStore(evictor=evictor)
    store.add_node(Node(name="n1", allocatable={"cpu": "4", "memory": "8Gi",
                                                "pods": 110}))
    store.add_priority_class(PriorityClass(name="high", value=100))

    store.add_pod_group(PodGroup(name="lo", min_member=2))
    store.pod_groups["default/lo"].status.phase = PodGroupPhase.Running.value
    store.add_pod(running_pod("lo-0", "lo", "2", "n1", priority=1))
    store.add_pod(running_pod("lo-1", "lo", "2", "n1", priority=1))

    store.add_pod_group(PodGroup(name="hi", min_member=1,
                                 priority_class="high"))
    store.pod_groups["default/hi"].status.phase = PodGroupPhase.Inqueue.value
    store.add_pod(pending_pod("hi-0", "hi", "4", priority=100))

    Scheduler(store, conf_str=PREEMPT_CONF).run_once()
    # Evicting one victim frees 2 cpu (< 4 needed); evicting both would
    # break the gang. No eviction should stick... the statement discards
    # partial evictions because the preemptor cannot be pipelined.
    assert evictor.evicts == []


def test_reclaim_cross_queue():
    evictor = FakeEvictor()
    store = ClusterStore(evictor=evictor)
    store.add_node(Node(name="n1", allocatable={"cpu": "4", "memory": "8Gi",
                                                "pods": 110}))
    store.add_queue(Queue(name="q1", weight=1, reclaimable=True))
    store.add_queue(Queue(name="q2", weight=1))

    # q1's job occupies the whole node.
    store.add_pod_group(PodGroup(name="owner", min_member=1, queue="q1"))
    store.pod_groups["default/owner"].status.phase = (
        PodGroupPhase.Running.value
    )
    store.add_pod(running_pod("owner-0", "owner", "2", "n1"))
    store.add_pod(running_pod("owner-1", "owner", "2", "n1"))

    # q2's job starves.
    store.add_pod_group(PodGroup(name="starved", min_member=1, queue="q2"))
    store.pod_groups["default/starved"].status.phase = (
        PodGroupPhase.Inqueue.value
    )
    store.add_pod(pending_pod("starved-0", "starved", "2"))

    Scheduler(store, conf_str=RECLAIM_CONF).run_once()
    assert len(evictor.evicts) == 1
    assert evictor.evicts[0].startswith("default/owner-")


def test_reclaim_respects_queue_reclaimable_false():
    evictor = FakeEvictor()
    store = ClusterStore(evictor=evictor)
    store.add_node(Node(name="n1", allocatable={"cpu": "4", "memory": "8Gi",
                                                "pods": 110}))
    store.add_queue(Queue(name="q1", weight=1, reclaimable=False))
    store.add_queue(Queue(name="q2", weight=1))

    store.add_pod_group(PodGroup(name="owner", min_member=1, queue="q1"))
    store.pod_groups["default/owner"].status.phase = (
        PodGroupPhase.Running.value
    )
    store.add_pod(running_pod("owner-0", "owner", "4", "n1"))

    store.add_pod_group(PodGroup(name="starved", min_member=1, queue="q2"))
    store.pod_groups["default/starved"].status.phase = (
        PodGroupPhase.Inqueue.value
    )
    store.add_pod(pending_pod("starved-0", "starved", "2"))

    Scheduler(store, conf_str=RECLAIM_CONF).run_once()
    assert evictor.evicts == []


def test_victim_set_persists_across_tiers():
    # Equal-priority preemptor vs victims: priority plugin yields no
    # victims in tier 1, which must poison later tiers' intersections
    # (session_plugins.go carries victims/init across tiers).
    evictor = FakeEvictor()
    store = ClusterStore(evictor=evictor)
    store.add_node(Node(name="n1", allocatable={"cpu": "4", "memory": "8Gi",
                                                "pods": 110}))
    store.add_pod_group(PodGroup(name="lo", min_member=1))
    store.pod_groups["default/lo"].status.phase = PodGroupPhase.Running.value
    store.add_pod(running_pod("lo-0", "lo", "2", "n1", priority=1))
    store.add_pod(running_pod("lo-1", "lo", "2", "n1", priority=1))
    store.add_pod_group(PodGroup(name="hi", min_member=1))
    store.pod_groups["default/hi"].status.phase = PodGroupPhase.Inqueue.value
    store.add_pod(pending_pod("hi-0", "hi", "2", priority=1))  # same priority

    Scheduler(store, conf_str=PREEMPT_CONF).run_once()
    assert evictor.evicts == []
