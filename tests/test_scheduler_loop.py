"""Periodic scheduler-loop behaviors.

The cycle itself is covered everywhere; these tests pin the LOOP's
contracts: GC suspension during cycles with the periodic full collect
between them, the leadership gate skipping cycles (and clearing stale
failure counts), and failure counting driving healthz.
"""

import gc
import threading
import time

import pytest

from volcano_tpu.api import GROUP_NAME_ANNOTATION, Node, Pod, PodGroup
from volcano_tpu.cache import ClusterStore
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.synth import synthetic_cluster


def small_store():
    return synthetic_cluster(n_nodes=4, n_pods=8, gang_size=2)


def test_gc_suspended_during_cycle_and_restored_after():
    seen = {"during": None}
    store = small_store()
    sched = Scheduler(store)
    orig = sched._run_once_inner

    def probe():
        seen["during"] = gc.isenabled()
        return orig()

    sched._run_once_inner = probe
    assert gc.isenabled()
    sched.run_once()
    assert seen["during"] is False  # suspended inside the cycle
    assert gc.isenabled()           # restored after


def test_gc_stays_disabled_if_caller_disabled_it():
    """run_once must not re-enable GC behind a caller that turned it
    off deliberately (e.g. a benchmark harness)."""
    store = small_store()
    sched = Scheduler(store)
    gc.disable()
    try:
        sched.run_once()
        assert not gc.isenabled()
    finally:
        gc.enable()


def test_loop_runs_full_collect_every_n_cycles(monkeypatch):
    collects = {"full": 0}
    real_collect = gc.collect

    def counting(generation=2):
        if generation == 2:
            collects["full"] += 1
        return real_collect(generation)

    monkeypatch.setattr(gc, "collect", counting)
    monkeypatch.setattr(Scheduler, "GC_FULL_EVERY", 3)
    store = small_store()
    sched = Scheduler(store, schedule_period=0.01)
    sched.run()
    try:
        deadline = time.time() + 5.0
        while collects["full"] < 2 and time.time() < deadline:
            time.sleep(0.02)
    finally:
        sched.stop()
    assert collects["full"] >= 2, "periodic full collect never ran"


def test_leadership_gate_skips_cycles_and_clears_failures():
    store = small_store()
    leading = threading.Event()
    sched = Scheduler(store, schedule_period=0.01,
                      gate=leading.is_set)
    # Simulate prior leader-era failures: standing by must clear them
    # (a standby's health check must not stay red).
    sched._consecutive_failures = sched.UNHEALTHY_AFTER
    assert not sched.healthy()
    sched.run()
    try:
        time.sleep(0.1)
        assert len(store.binder.binds) == 0  # no cycles while standby
        assert sched.healthy()               # failures cleared
        leading.set()
        deadline = time.time() + 5.0
        while len(store.binder.binds) < 8 and time.time() < deadline:
            time.sleep(0.02)
    finally:
        sched.stop()
    assert len(store.binder.binds) == 8


def test_stop_joins_thread_and_drains_inflight_dispatch():
    """stop() must leave the loop thread DEAD (not a timed-out join that
    silently leaks a scheduling thread behind a restart) and must drain
    the pipelined dispatch parked between cycles — the solved pods stay
    Pending and re-place after a restart."""
    import numpy as np

    from volcano_tpu.api import TaskStatus

    store = small_store()
    store.pipeline = True
    st_bound = int(TaskStatus.Bound)

    # Steady-state feed: re-pend whatever the commit just bound, so every
    # cycle dispatches a fresh solve and an in-flight handle is parked
    # whenever the loop is between cycles.
    def feed(fc):
        rows = np.flatnonzero(
            (fc.m.p_status[:fc.Pn] == st_bound) & fc.m.p_alive[:fc.Pn]
        )
        if len(rows):
            fc._unbind_rows(rows)

    store.cycle_feed = feed
    sched = Scheduler(store, schedule_period=0.01)
    sched.run()
    t = sched._thread
    assert t is not None
    deadline = time.time() + 10.0
    while (getattr(store, "_inflight_solve", None) is None
           and time.time() < deadline):
        time.sleep(0.005)
    assert store._inflight_solve is not None, "no dispatch ever parked"
    sched.stop()
    assert not t.is_alive()          # the loop thread is DEAD
    assert sched._thread is None     # and not retained for a re-join
    # The parked device future was abandoned, not leaked.
    assert getattr(store, "_inflight_solve", None) is None


def test_repeated_failures_flip_healthz(monkeypatch):
    store = small_store()
    sched = Scheduler(store, schedule_period=0.01)

    def boom():
        raise RuntimeError("cycle exploded")

    sched.run_once = boom
    assert sched.healthy()
    sched.run()
    try:
        deadline = time.time() + 5.0
        while sched.healthy() and time.time() < deadline:
            time.sleep(0.02)
    finally:
        sched.stop()
    assert not sched.healthy()
