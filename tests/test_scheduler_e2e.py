"""End-to-end scheduler cycle tests.

Mirrors the reference's action tests
(pkg/scheduler/actions/allocate/allocate_test.go:155-222): build a cluster
through the store with fake binder, run a full session cycle with real
plugins, assert the bind map.
"""

import pytest

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    PodGroupPhase,
    PodPhase,
    Queue,
)
from volcano_tpu.cache import ClusterStore, FakeBinder
from volcano_tpu.framework import parse_scheduler_conf
from volcano_tpu.scheduler import Scheduler


def make_pod(name, group, cpu="1", mem="1Gi", ns="default", **kw):
    return Pod(
        name=name,
        namespace=ns,
        annotations={GROUP_NAME_ANNOTATION: group},
        containers=[{"cpu": cpu, "memory": mem}],
        **kw,
    )


def make_node(name, cpu="4", mem="8Gi"):
    return Node(name=name, allocatable={"cpu": cpu, "memory": mem, "pods": 110})


def test_single_gang_job_binds_all():
    binder = FakeBinder()
    store = ClusterStore(binder=binder)
    store.add_node(make_node("n1"))
    store.add_node(make_node("n2"))
    store.add_pod_group(PodGroup(name="pg1", min_member=3))
    for i in range(3):
        store.add_pod(make_pod(f"p{i}", "pg1", cpu="2", mem="2Gi"))

    Scheduler(store).run_once()

    assert len(binder.binds) == 3, binder.binds
    # PodGroup phase advanced to Running at close.
    assert (
        store.pod_groups["default/pg1"].status.phase
        == PodGroupPhase.Running.value
    )


def test_gang_job_does_not_partially_bind():
    binder = FakeBinder()
    store = ClusterStore(binder=binder)
    store.add_node(make_node("n1", cpu="4"))
    store.add_pod_group(PodGroup(name="pg1", min_member=3))
    for i in range(3):
        store.add_pod(make_pod(f"p{i}", "pg1", cpu="2", mem="1Gi"))

    Scheduler(store).run_once()
    assert binder.binds == {}
    # Unschedulable condition recorded by the gang plugin.
    conditions = store.pod_groups["default/pg1"].status.conditions
    assert any(c.type == "Unschedulable" for c in conditions)


def test_two_jobs_two_queues_fair_start():
    binder = FakeBinder()
    store = ClusterStore(binder=binder)
    for i in range(4):
        store.add_node(make_node(f"n{i}"))
    store.add_queue(Queue(name="q1", weight=2))
    store.add_queue(Queue(name="q2", weight=2))
    store.add_pod_group(PodGroup(name="pga", min_member=2, queue="q1"))
    store.add_pod_group(PodGroup(name="pgb", min_member=2, queue="q2"))
    for i in range(2):
        store.add_pod(make_pod(f"a{i}", "pga", cpu="2"))
        store.add_pod(make_pod(f"b{i}", "pgb", cpu="2"))

    Scheduler(store).run_once()
    assert len(binder.binds) == 4


def test_enqueue_gates_pending_podgroups():
    # A PodGroup with MinResources beyond overcommitted capacity stays
    # Pending and its pods are not scheduled this cycle.
    binder = FakeBinder()
    store = ClusterStore(binder=binder)
    store.add_node(make_node("n1", cpu="4", mem="8Gi"))
    store.add_pod_group(
        PodGroup(name="big", min_member=1,
                 min_resources={"cpu": "100", "memory": "1Gi"})
    )
    store.add_pod(make_pod("p0", "big", cpu="1"))
    Scheduler(store).run_once()
    assert binder.binds == {}
    assert (
        store.pod_groups["default/big"].status.phase
        == PodGroupPhase.Pending.value
    )

    # A modest job passes the gate and schedules in the same cycle flow.
    store.add_pod_group(
        PodGroup(name="small", min_member=1,
                 min_resources={"cpu": "1", "memory": "1Gi"})
    )
    store.add_pod(make_pod("s0", "small", cpu="1"))
    Scheduler(store).run_once()
    assert "default/s0" in binder.binds


def test_backfill_places_besteffort_tasks():
    binder = FakeBinder()
    store = ClusterStore(binder=binder)
    store.add_node(make_node("n1"))
    store.add_pod_group(PodGroup(name="pg1", min_member=1))
    store.add_pod(
        Pod(
            name="be0",
            annotations={GROUP_NAME_ANNOTATION: "pg1"},
            containers=[{}],  # zero request: BestEffort
        )
    )
    Scheduler(store).run_once()
    assert "default/be0" in binder.binds


def test_node_selector_respected_e2e():
    binder = FakeBinder()
    store = ClusterStore(binder=binder)
    store.add_node(Node(name="n1", allocatable={"cpu": "4", "memory": "8Gi"},
                        labels={"zone": "a"}))
    store.add_node(Node(name="n2", allocatable={"cpu": "4", "memory": "8Gi"},
                        labels={"zone": "b"}))
    store.add_pod_group(PodGroup(name="pg1", min_member=1))
    pod = make_pod("p0", "pg1")
    pod.node_selector = {"zone": "b"}
    store.add_pod(pod)
    Scheduler(store).run_once()
    assert binder.binds.get("default/p0") == "n2"


def test_binpack_conf_packs_tasks():
    conf = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: binpack
"""
    binder = FakeBinder()
    store = ClusterStore(binder=binder)
    store.add_node(make_node("n1", cpu="8", mem="16Gi"))
    store.add_node(make_node("n2", cpu="8", mem="16Gi"))
    store.add_pod_group(PodGroup(name="pg1", min_member=2))
    for i in range(2):
        store.add_pod(make_pod(f"p{i}", "pg1", cpu="1", mem="1Gi"))
    Scheduler(store, conf_str=conf).run_once()
    nodes = set(binder.binds.values())
    assert len(nodes) == 1  # packed onto one node


def test_priority_order_prefers_high_priority_job():
    # Two 1-task jobs compete for one slot; higher priority job wins.
    binder = FakeBinder()
    store = ClusterStore(binder=binder)
    store.add_node(make_node("n1", cpu="2", mem="4Gi"))
    from volcano_tpu.api import PriorityClass

    store.add_priority_class(PriorityClass(name="high", value=100))
    store.add_pod_group(PodGroup(name="lo", min_member=1))
    store.add_pod_group(
        PodGroup(name="hi", min_member=1, priority_class="high")
    )
    store.add_pod(make_pod("lo-0", "lo", cpu="2"))
    store.add_pod(make_pod("hi-0", "hi", cpu="2"))
    Scheduler(store).run_once()
    assert "default/hi-0" in binder.binds
    assert "default/lo-0" not in binder.binds


def test_conf_parsing_flags():
    conf = parse_scheduler_conf(
        """
actions: "enqueue, allocate"
tiers:
- plugins:
  - name: priority
    enableJobOrder: false
  - name: gang
configurations:
- name: enqueue
  arguments:
    overcommit-factor: "1.5"
"""
    )
    assert conf.actions == "enqueue, allocate"
    prio = conf.tiers[0].plugins[0]
    assert prio.enabled_job_order is False
    assert prio.enabled_task_order is True  # defaulted
    assert conf.configurations[0].arguments["overcommit-factor"] == "1.5"


def test_fastpath_failure_fallback_guard(monkeypatch):
    """Small clusters fall back to the object session when the fast path
    fails; VOLCANO_TPU_FALLBACK=never (or a hyperscale mirror) re-raises
    instead of stalling in an O(tasks x nodes) Python walk."""
    import volcano_tpu.fastpath as fp
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.synth import synthetic_cluster

    def boom(store, conf, shard=None):
        raise RuntimeError("device exploded")

    monkeypatch.setattr(fp, "run_cycle_fast", boom)

    # conftest pins FALLBACK=never for the suite; this test exercises
    # the production default.
    monkeypatch.setenv("VOLCANO_TPU_FALLBACK", "auto")
    store = synthetic_cluster(n_nodes=4, n_pods=8, gang_size=2)
    Scheduler(store).run_once()  # falls back, still binds
    assert len(store.binder.binds) == 8

    monkeypatch.setenv("VOLCANO_TPU_FALLBACK", "never")
    store2 = synthetic_cluster(n_nodes=4, n_pods=8, gang_size=2)
    import pytest

    with pytest.raises(RuntimeError, match="device exploded"):
        Scheduler(store2).run_once()


def test_fastpath_failure_no_fallback_at_hyperscale(monkeypatch):
    """auto mode refuses the object-session fallback when tasks x nodes
    exceeds FALLBACK_MAX_WORK (the hours-long Python walk)."""
    import volcano_tpu.fastpath as fp
    from volcano_tpu.cache.mirror import StoreMirror
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.synth import synthetic_cluster

    def boom(store, conf, shard=None):
        raise RuntimeError("device exploded")

    monkeypatch.setattr(fp, "run_cycle_fast", boom)
    # 8 real pending tasks x a faked 10M-node cluster exceeds the
    # pending x nodes work bound.
    monkeypatch.setattr(StoreMirror, "n_nodes",
                        property(lambda self: 10_000_000))
    store = synthetic_cluster(n_nodes=4, n_pods=8, gang_size=2)
    import pytest

    with pytest.raises(RuntimeError, match="device exploded"):
        Scheduler(store).run_once()


def test_conf_hot_reload_between_cycles(tmp_path):
    """The YAML config is re-read every cycle (scheduler.go:77,89-106):
    enabling the preempt action in the file takes effect on the next
    run_once without restarting the scheduler."""
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.synth import preempt_cluster

    base = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""
    conf = tmp_path / "scheduler.conf"
    conf.write_text(base)
    store = preempt_cluster(n_nodes=6, n_pending=12, seed=2)
    sched = Scheduler(store, conf_path=str(conf))
    sched.run_once()
    assert len(store.evictor.evicts) == 0  # no preempt action yet
    conf.write_text(base.replace(
        '"enqueue, allocate, backfill"',
        '"enqueue, allocate, preempt, reclaim, backfill"',
    ))
    sched.run_once()
    assert len(store.evictor.evicts) > 0  # hot-reloaded action ran


def test_conf_parse_failure_keeps_last_good(tmp_path):
    """A broken config edit keeps the last GOOD config (scheduler.go
    keeps scheduling on parse failure) — distinguishable from the
    built-in default because the good config enables preempt, which the
    default does not."""
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.synth import preempt_cluster

    conf = tmp_path / "scheduler.conf"
    conf.write_text("""
actions: "enqueue, allocate, preempt, reclaim, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
""")
    store = preempt_cluster(n_nodes=6, n_pending=12, seed=4)
    sched = Scheduler(store, conf_path=str(conf))
    sched.run_once()
    evicted_first = len(store.evictor.evicts)
    assert evicted_first > 0
    conf.write_text("actions: [unclosed")
    store2 = preempt_cluster(n_nodes=6, n_pending=12, seed=4)
    sched.store = store2
    sched.run_once()  # parse fails -> last good config (with preempt)
    assert len(store2.evictor.evicts) == evicted_first


def test_namespace_weighted_fair_share():
    """Weighted namespace DRF (drf.go:224-258 + namespace_info.go:33-37):
    with capacity for only part of the demand, the heavier namespace's
    jobs are ordered first and scheduled; the lighter namespace waits.
    Mirrors the reference's namespace fair-share e2e
    (job_scheduling.go namespace affinity case)."""
    from volcano_tpu.api import (GROUP_NAME_ANNOTATION, Node, Pod, PodGroup,
                                 ResourceQuota)
    from volcano_tpu.cache import ClusterStore
    from volcano_tpu.scheduler import Scheduler

    conf = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: gang
- plugins:
  - name: drf
    arguments:
      drf.enableNamespaceOrder: true
  - name: binpack
"""
    def run(w_heavy, w_light):
        store = ClusterStore()
        # 6 cpus: 2 taken by running pods; room for 4 of 8 pending.
        store.add_node(Node(name="n0", allocatable={"cpu": "6",
                                                    "memory": "24Gi"}))
        store.add_resource_quota(ResourceQuota(
            name="qh", namespace="heavy",
            annotations={"volcano-tpu/namespace.weight": str(w_heavy)},
        ))
        store.add_resource_quota(ResourceQuota(
            name="ql", namespace="light",
            annotations={"volcano-tpu/namespace.weight": str(w_light)},
        ))
        for ns in ("light", "heavy"):
            # One running pod each: equal raw shares, so the WEIGHTED
            # share (share/weight) decides the namespace order — an
            # all-pending tie would be settled by the name tie-break
            # instead, hiding the weights.
            store.add_pod_group(PodGroup(name=f"{ns}-run", namespace=ns,
                                         min_member=1))
            store.add_pod(Pod(
                name=f"{ns}-r0", namespace=ns,
                containers=[{"cpu": "1", "memory": "1Gi"}],
                annotations={GROUP_NAME_ANNOTATION: f"{ns}-run"},
                node_name="n0", phase="Running",
            ))
            store.add_pod_group(PodGroup(name=f"{ns}-g", namespace=ns,
                                         min_member=1))
            for k in range(4):
                store.add_pod(Pod(
                    name=f"{ns}-p{k}", namespace=ns,
                    containers=[{"cpu": "1", "memory": "1Gi"}],
                    annotations={GROUP_NAME_ANNOTATION: f"{ns}-g"},
                ))
        Scheduler(store, conf_str=conf).run_once()
        out = {}
        for key in store.binder.binds:
            out[key.split("/")[0]] = out.get(key.split("/")[0], 0) + 1
        return out

    # The heavier namespace's weighted share is smaller -> ordered first.
    assert run(8, 1) == {"heavy": 4}
    # Swapping the weights flips the winner (the test is not decided by
    # name tie-breaks or insertion order).
    assert run(1, 8) == {"light": 4}
