"""Job-controller event-handler tables.

The analog of ``pkg/controllers/job/job_controller_handler_test.go``:
store events (pod update/evict/delete, job add/update, PodGroup status,
node health, commands) must map to the right reconcile Requests —
correct event type, task attribution, exit code, and job-version
stamping — and ownerless pods must be ignored.
"""

import copy

import pytest

from volcano_tpu.api import Node, Pod, PodPhase
from volcano_tpu.cache import ClusterStore
from volcano_tpu.controllers import Job, JobController, TaskSpec
from volcano_tpu.controllers.apis import Command, Event


def make_store():
    s = ClusterStore()
    s.add_node(Node(name="n0", allocatable={"cpu": "16", "memory": "32Gi",
                                            "pods": 110}))
    return s


def owned_pod(name="j1-worker-0", version=3, **kw):
    kw.setdefault("containers", [{"cpu": "1", "memory": "1Gi"}])
    return Pod(
        name=name,
        owner_job="default/j1",
        task_name="worker",
        annotations={"volcano-tpu/job-version": str(version)},
        **kw,
    )


def drain(jc):
    out = list(jc.queue)
    jc.queue.clear()
    return out


def test_job_add_and_update_enqueue_outofsync():
    s = make_store()
    jc = JobController(s)
    job = Job(name="j1", min_available=1,
              tasks=[TaskSpec(name="worker", replicas=1,
                              containers=[{"cpu": "1"}])])
    s.add_batch_job(job)
    reqs = drain(jc)
    assert [r.event for r in reqs] == [Event.OutOfSync.value]
    s.update_batch_job(job)
    reqs = drain(jc)
    assert [r.event for r in reqs] == [Event.OutOfSync.value]
    assert reqs[0].job_name == "j1"


@pytest.mark.parametrize("phase,exit_code,expected_event,has_task", [
    (PodPhase.Failed, 137, Event.PodFailed.value, True),
    (PodPhase.Succeeded, 0, Event.TaskCompleted.value, True),
    (PodPhase.Running, 0, Event.OutOfSync.value, False),
    (PodPhase.Pending, 0, Event.OutOfSync.value, False),
])
def test_pod_update_event_table(phase, exit_code, expected_event,
                                has_task):
    """job_controller_handler.go updatePod: terminal phases fire
    lifecycle events with task attribution + exit code; everything else
    degrades to sync."""
    s = make_store()
    jc = JobController(s)
    pod = owned_pod(phase=PodPhase.Running, node_name="n0")
    s.add_pod(pod)
    drain(jc)
    upd = copy.copy(pod)
    upd.phase = phase
    upd.exit_code = exit_code
    s.update_pod(upd)
    reqs = drain(jc)
    assert len(reqs) == 1
    r = reqs[0]
    assert r.event == expected_event
    assert r.namespace == "default" and r.job_name == "j1"
    if has_task:
        assert r.task_name == "worker"
        assert r.job_version == 3
    if expected_event == Event.PodFailed.value:
        assert r.exit_code == 137


def test_pod_evict_event_fires_podevicted():
    s = make_store()
    jc = JobController(s)
    pod = owned_pod(phase=PodPhase.Running, node_name="n0")
    s.add_pod(pod)
    drain(jc)
    s._notify("Pod", "evict", pod)
    reqs = drain(jc)
    assert [r.event for r in reqs] == [Event.PodEvicted.value]
    assert reqs[0].task_name == "worker"
    assert reqs[0].job_version == 3


def test_pod_delete_degrades_to_sync():
    s = make_store()
    jc = JobController(s)
    pod = owned_pod(phase=PodPhase.Running, node_name="n0")
    s.add_pod(pod)
    drain(jc)
    s.delete_pod(pod)
    reqs = drain(jc)
    assert [r.event for r in reqs] == [Event.OutOfSync.value]


def test_ownerless_pod_events_ignored():
    """Bare pods (no owner job) never reach the job controller's
    queue — the podgroup controller owns them."""
    s = make_store()
    jc = JobController(s)
    pod = Pod(name="bare-0", containers=[{"cpu": "1", "memory": "1Gi"}],
              phase=PodPhase.Running, node_name="n0")
    s.add_pod(pod)
    upd = copy.copy(pod)
    upd.phase = PodPhase.Failed
    s.update_pod(upd)
    s.delete_pod(upd)
    assert drain(jc) == []


def test_node_notready_raises_deviceunhealthy_per_resident_job():
    """TPU-native: a node going NotReady fires DeviceUnhealthy for each
    job with pods resident on it (SURVEY.md 5.3)."""
    s = make_store()
    jc = JobController(s)
    pod = owned_pod(phase=PodPhase.Running, node_name="n0")
    s.add_pod(pod)
    drain(jc)
    down = Node(name="n0", allocatable={"cpu": "16", "memory": "32Gi",
                                        "pods": 110}, ready=False)
    s.update_node(down)
    reqs = drain(jc)
    assert Event.DeviceUnhealthy.value in [r.event for r in reqs]
    du = next(r for r in reqs if r.event == Event.DeviceUnhealthy.value)
    assert du.job_name == "j1"
    assert du.task_name == "worker"


def test_node_ready_update_is_quiet():
    s = make_store()
    jc = JobController(s)
    pod = owned_pod(phase=PodPhase.Running, node_name="n0")
    s.add_pod(pod)
    drain(jc)
    s.update_node(Node(name="n0",
                       allocatable={"cpu": "32", "memory": "32Gi"}))
    assert all(r.event != Event.DeviceUnhealthy.value
               for r in drain(jc))


def test_podgroup_status_event_syncs_owner_job():
    s = make_store()
    jc = JobController(s)
    job = Job(name="j1", min_available=1,
              tasks=[TaskSpec(name="worker", replicas=1,
                              containers=[{"cpu": "1"}])])
    s.add_batch_job(job)
    jc.process_all()
    pg = s.pod_groups["default/j1"]
    drain(jc)
    s._notify("PodGroup", "status", pg)
    reqs = drain(jc)
    assert [r.event for r in reqs] == [Event.OutOfSync.value]
    assert reqs[0].job_name == "j1"


def test_command_routes_action_and_is_consumed():
    """bus API: a Job-targeted Command becomes a CommandIssued request
    carrying the action, and the command record is deleted (owned by
    its delivery)."""
    s = make_store()
    jc = JobController(s)
    cmd = Command(action="AbortJob", target_kind="Job",
                  target_name="j1", name="cmd-1")
    s.add_command(cmd)
    reqs = drain(jc)
    assert [(r.event, r.action) for r in reqs] == [
        (Event.CommandIssued.value, "AbortJob")
    ]
    assert not s.commands  # consumed


def test_queue_command_not_routed_to_job_controller():
    s = make_store()
    jc = JobController(s)
    s.add_command(Command(action="CloseQueue", target_kind="Queue",
                          target_name="q1", name="cmd-q"))
    assert all(r.event != Event.CommandIssued.value for r in drain(jc))
