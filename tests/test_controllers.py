"""Controller-plane lifecycle tests.

Mirrors the reference's controller tests (job_state_test.go table style +
e2e lifecycle flows from test/e2e/job_error_handling.go) against the
simulated cluster: submit -> enqueue gate -> pods -> bind -> run ->
policies/commands -> terminal phases.
"""

import pytest

from volcano_tpu.api import GROUP_NAME_ANNOTATION, Node, Pod, PodGroupPhase, PodPhase
from volcano_tpu.cache import ClusterStore, FakeBinder
from volcano_tpu.controllers import (
    Action,
    Command,
    ControllerManager,
    Event,
    Job,
    JobPhase,
    LifecyclePolicy,
    TaskSpec,
)
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.sim import ClusterSimulator


def make_env(n_nodes=2):
    store = ClusterStore()
    for i in range(n_nodes):
        store.add_node(
            Node(name=f"n{i}", allocatable={"cpu": "8", "memory": "16Gi",
                                            "pods": 110})
        )
    cm = ControllerManager(store)
    sched = Scheduler(store)
    sim = ClusterSimulator(store)
    return store, cm, sched, sim


def simple_job(name="j1", replicas=2, min_available=2, policies=None,
               task_policies=None, plugins=None):
    return Job(
        name=name,
        min_available=min_available,
        tasks=[
            TaskSpec(
                name="worker",
                replicas=replicas,
                containers=[{"cpu": "1", "memory": "1Gi"}],
                policies=task_policies or [],
            )
        ],
        policies=policies or [],
        plugins=plugins or {},
    )


def converge(cm, sched, sim, cycles=4, complete=None):
    for _ in range(cycles):
        cm.process()
        sched.run_once()
        sim.step(complete=complete)
        cm.process()


def test_job_lifecycle_to_running():
    store, cm, sched, sim = make_env()
    job = simple_job()
    store.add_batch_job(job)

    cm.process()
    # PodGroup created; pod creation gated until Inqueue.
    assert "default/j1" in store.pod_groups
    assert not [p for p in store.pods.values() if p.owner_job == "default/j1"]

    converge(cm, sched, sim)
    job = store.batch_jobs["default/j1"]
    assert job.status.state.phase == JobPhase.Running.value
    assert job.status.running == 2


def test_job_completes_when_all_succeed():
    store, cm, sched, sim = make_env()
    store.add_batch_job(simple_job())
    converge(cm, sched, sim)
    # All pods succeed.
    converge(cm, sched, sim, complete=lambda pod: 0)
    job = store.batch_jobs["default/j1"]
    assert job.status.state.phase == JobPhase.Completed.value


def test_pod_failure_restart_policy():
    store, cm, sched, sim = make_env()
    store.add_batch_job(
        simple_job(
            policies=[LifecyclePolicy(action=Action.RestartJob.value,
                                      event=Event.PodFailed.value)]
        )
    )
    converge(cm, sched, sim)
    assert store.batch_jobs["default/j1"].status.state.phase == (
        JobPhase.Running.value
    )
    # Fail one pod.
    uid = next(
        p.uid for p in store.pods.values() if p.owner_job == "default/j1"
    )
    sim.fail_pod(uid, exit_code=137)
    cm.process()
    job = store.batch_jobs["default/j1"]
    assert job.status.state.phase == JobPhase.Restarting.value
    assert job.status.retry_count == 1
    # Let terminations drain and the job re-run.
    converge(cm, sched, sim, cycles=6)
    job = store.batch_jobs["default/j1"]
    assert job.status.state.phase == JobPhase.Running.value


def test_pod_failure_default_is_sync():
    # Without a policy, PodFailed just syncs; job keeps running with a
    # failed count.
    store, cm, sched, sim = make_env()
    store.add_batch_job(simple_job(min_available=1))
    converge(cm, sched, sim)
    uid = next(
        p.uid for p in store.pods.values() if p.owner_job == "default/j1"
    )
    sim.fail_pod(uid)
    cm.process()
    job = store.batch_jobs["default/j1"]
    assert job.status.state.phase == JobPhase.Running.value
    assert job.status.failed == 1


def test_exit_code_policy():
    store, cm, sched, sim = make_env()
    store.add_batch_job(
        simple_job(
            policies=[LifecyclePolicy(action=Action.AbortJob.value,
                                      exit_code=137)]
        )
    )
    converge(cm, sched, sim)
    uid = next(
        p.uid for p in store.pods.values() if p.owner_job == "default/j1"
    )
    sim.fail_pod(uid, exit_code=137)
    cm.process()
    assert store.batch_jobs["default/j1"].status.state.phase == (
        JobPhase.Aborting.value
    )
    converge(cm, sched, sim, cycles=3)
    assert store.batch_jobs["default/j1"].status.state.phase == (
        JobPhase.Aborted.value
    )


def test_task_level_policy_overrides_job_level():
    store, cm, sched, sim = make_env()
    job = simple_job(
        policies=[LifecyclePolicy(action=Action.AbortJob.value,
                                  event=Event.PodFailed.value)],
        task_policies=[LifecyclePolicy(action=Action.RestartJob.value,
                                       event=Event.PodFailed.value)],
    )
    store.add_batch_job(job)
    converge(cm, sched, sim)
    uid = next(
        p.uid for p in store.pods.values() if p.owner_job == "default/j1"
    )
    sim.fail_pod(uid)
    cm.process()
    assert store.batch_jobs["default/j1"].status.state.phase == (
        JobPhase.Restarting.value
    )


def test_command_abort_and_resume():
    store, cm, sched, sim = make_env()
    store.add_batch_job(simple_job())
    converge(cm, sched, sim)

    store.add_command(Command(action=Action.AbortJob.value,
                              target_kind="Job", target_name="j1"))
    cm.process()
    assert store.batch_jobs["default/j1"].status.state.phase == (
        JobPhase.Aborting.value
    )
    converge(cm, sched, sim, cycles=3)
    assert store.batch_jobs["default/j1"].status.state.phase == (
        JobPhase.Aborted.value
    )

    store.add_command(Command(action=Action.ResumeJob.value,
                              target_kind="Job", target_name="j1"))
    cm.process()
    converge(cm, sched, sim, cycles=6)
    job = store.batch_jobs["default/j1"]
    assert job.status.state.phase == JobPhase.Running.value


def test_max_retry_leads_to_failed():
    store, cm, sched, sim = make_env()
    job = simple_job(
        policies=[LifecyclePolicy(action=Action.RestartJob.value,
                                  event=Event.PodFailed.value)],
    )
    job.max_retry = 1
    store.add_batch_job(job)
    converge(cm, sched, sim)

    uid = next(
        p.uid for p in store.pods.values() if p.owner_job == "default/j1"
    )
    sim.fail_pod(uid)
    # retry_count becomes 1 == max_retry, so the restarting state goes
    # straight to Failed (restarting.go: retryCount >= maxRetry).
    converge(cm, sched, sim, cycles=4)
    job = store.batch_jobs["default/j1"]
    assert job.status.state.phase == JobPhase.Failed.value


def test_scale_up_and_down():
    store, cm, sched, sim = make_env()
    store.add_batch_job(simple_job(replicas=2, min_available=2))
    converge(cm, sched, sim)
    assert len([p for p in store.pods.values()
                if p.owner_job == "default/j1"]) == 2

    job = store.batch_jobs["default/j1"]
    job.tasks[0].replicas = 4
    store.update_batch_job(job)
    converge(cm, sched, sim)
    assert len([p for p in store.pods.values()
                if p.owner_job == "default/j1"]) == 4

    job.tasks[0].replicas = 1
    store.update_batch_job(job)
    converge(cm, sched, sim, cycles=3)
    alive = [
        p for p in store.pods.values()
        if p.owner_job == "default/j1" and not p.deleting
    ]
    assert len(alive) == 1


def test_podgroup_controller_wraps_bare_pod():
    store, cm, sched, sim = make_env()
    store.add_pod(Pod(name="bare", containers=[{"cpu": "1",
                                                "memory": "1Gi"}]))
    cm.process()
    pod = next(p for p in store.pods.values() if p.name == "bare")
    group = pod.annotations.get(GROUP_NAME_ANNOTATION)
    assert group
    pg = store.pod_groups[f"default/{group}"]
    assert pg.min_member == 1
    # It now schedules.
    sched.run_once()
    assert store.binder.binds.get("default/bare")


def test_ttl_garbage_collection():
    store, cm, sched, sim = make_env()
    job = simple_job()
    job.ttl_seconds_after_finished = 0.0
    store.add_batch_job(job)
    converge(cm, sched, sim)
    converge(cm, sched, sim, complete=lambda pod: 0)
    # ttl=0: eligible for deletion immediately after finishing; the GC
    # sweep inside the reconcile pump collects it.
    cm.gc.sweep()
    assert "default/j1" not in store.batch_jobs
    # Cascading cleanup removed the pods and PodGroup too.
    cm.process()
    sim.step()
    assert not [p for p in store.pods.values()
                if p.owner_job == "default/j1" and not p.deleting]
    assert "default/j1" not in store.pod_groups


def test_rendezvous_plugins_inject_env():
    store, cm, sched, sim = make_env()
    job = Job(
        name="mpi",
        min_available=3,
        tasks=[
            TaskSpec(name="master", replicas=1,
                     containers=[{"cpu": "1", "memory": "1Gi"}]),
            TaskSpec(name="worker", replicas=2,
                     containers=[{"cpu": "1", "memory": "1Gi"}]),
        ],
        plugins={"svc": [], "ssh": [], "env": []},
    )
    store.add_batch_job(job)
    converge(cm, sched, sim)

    pods = [p for p in store.pods.values() if p.owner_job == "default/mpi"]
    assert len(pods) == 3
    worker = next(p for p in pods if p.task_name == "worker")
    assert worker.env["MASTER_HOSTS"] == "mpi-master-0.mpi"
    assert worker.env["WORKER_NUM"] == "2"
    assert worker.env["VC_PROCESS_COUNT"] == "3"
    assert worker.env["VC_COORDINATOR_ADDRESS"].startswith("mpi-master-0.mpi:")
    assert "VK_TASK_INDEX" in worker.env
    # Hosts ConfigMap + ssh secret exist.
    assert "worker.host" in store.config_maps["default/mpi-svc"]
    assert "id_rsa" in store.secrets["default/mpi-ssh"]
    # Distinct process ids across the gang.
    ids = sorted(p.env["VC_PROCESS_ID"] for p in pods)
    assert ids == ["0", "1", "2"]


def test_policies_survive_version_bump():
    # After a restart (version bump), a second PodFailed must still fire
    # the RestartJob policy (pods carry the job-version annotation).
    store, cm, sched, sim = make_env()
    store.add_batch_job(
        simple_job(
            policies=[LifecyclePolicy(action=Action.RestartJob.value,
                                      event=Event.PodFailed.value)]
        )
    )
    converge(cm, sched, sim)
    uid = next(p.uid for p in store.pods.values()
               if p.owner_job == "default/j1")
    sim.fail_pod(uid)
    converge(cm, sched, sim, cycles=6)
    assert store.batch_jobs["default/j1"].status.state.phase == (
        JobPhase.Running.value
    )
    assert store.batch_jobs["default/j1"].status.retry_count == 1
    # Second failure after restart: policy must fire again.
    uid = next(p.uid for p in store.pods.values()
               if p.owner_job == "default/j1"
               and p.phase == PodPhase.Running)
    sim.fail_pod(uid)
    cm.process()
    assert store.batch_jobs["default/j1"].status.retry_count == 2


def test_ssh_keys_stable_across_syncs():
    store, cm, sched, sim = make_env()
    store.add_batch_job(simple_job(plugins={"ssh": []}))
    converge(cm, sched, sim)
    key1 = store.secrets["default/j1-ssh"]["id_rsa"]
    converge(cm, sched, sim, cycles=3)
    assert store.secrets["default/j1-ssh"]["id_rsa"] == key1


def test_device_unhealthy_policy():
    store, cm, sched, sim = make_env()
    store.add_batch_job(
        simple_job(
            policies=[LifecyclePolicy(action=Action.RestartJob.value,
                                      event=Event.DeviceUnhealthy.value)]
        )
    )
    converge(cm, sched, sim)
    assert store.batch_jobs["default/j1"].status.state.phase == (
        JobPhase.Running.value
    )
    node = next(p.node_name for p in store.pods.values()
                if p.owner_job == "default/j1")
    sim.fail_node(node)
    cm.process()
    job = store.batch_jobs["default/j1"]
    assert job.status.state.phase == JobPhase.Restarting.value


def test_min_resources_include_scalars():
    from volcano_tpu.controllers.job_controller import JobController

    store, cm, sched, sim = make_env()
    job = Job(
        name="tj",
        min_available=2,
        tasks=[TaskSpec(name="w", replicas=2,
                        containers=[{"cpu": "1", "memory": "1Gi",
                                     "tpu.dev/chips": 4}])],
    )
    store.add_batch_job(job)
    cm.process()
    pg = store.pod_groups["default/tj"]
    assert "tpu.dev/chips" in pg.min_resources


def test_tpuslice_plugin_packs_gang_into_one_slice():
    """SURVEY.md 2.4 item 4: TPU slice topology is a first-class node
    attribute used by placement scoring.  Four 1-cpu tasks fit 2-per-node;
    with the tpuslice job plugin they must co-locate on the two nodes of a
    single slice rather than spreading across slices."""
    from volcano_tpu.api import Node
    from volcano_tpu.cache import ClusterStore
    from volcano_tpu.controllers import ControllerManager
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.sim import ClusterSimulator

    store = ClusterStore()
    for i in range(4):
        store.add_node(Node(
            name=f"tpu-{i}",
            allocatable={"cpu": "2", "memory": "8Gi", "pods": 16},
            topology={"volcano-tpu/slice": f"slice-{i // 2}"},
        ))
    cm = ControllerManager(store)
    sched = Scheduler(store)
    sim = ClusterSimulator(store)
    job = simple_job(name="train", replicas=4, min_available=4,
                     plugins={"tpuslice": []})
    store.add_batch_job(job)
    converge(cm, sched, sim)

    pods = [p for p in store.pods.values()
            if p.owner_job == "default/train"]
    assert len(pods) == 4
    slices = set()
    for p in pods:
        assert p.node_name, f"pod {p.name} unbound"
        idx = int(p.node_name.split("-")[1])
        slices.add(idx // 2)
    assert len(slices) == 1, f"gang split across slices: {slices}"
    # The injected term is visible on the pod spec.
    term, weight = pods[0].preferred_affinity[0]
    assert term.topology_key == "volcano-tpu/slice"
    assert weight == 10


def test_node_topology_folds_into_labels():
    from volcano_tpu.api import Node

    n = Node(name="n", allocatable={"cpu": "1"},
             labels={"zone": "z1"},
             topology={"volcano-tpu/slice": "s0", "zone": "explicit-wins"})
    assert n.labels["volcano-tpu/slice"] == "s0"
    assert n.labels["zone"] == "z1"  # explicit label wins collision


def test_queue_close_open_lifecycle_via_commands():
    """CloseQueue/OpenQueue commands drive the queue state machine
    (queue_controller.go:268-330): a closed queue rejects new jobs at
    admission while running jobs continue; reopening admits again."""
    import pytest

    from volcano_tpu.api import Queue
    from volcano_tpu.controllers import Command
    from volcano_tpu.webhooks.admission import AdmissionError

    store, cm, sched, sim = make_env()
    store.add_queue(Queue(name="batch", weight=2))
    from volcano_tpu.webhooks.admission import AdmittedStore

    admitted = AdmittedStore(store)
    job1 = simple_job(name="j1", replicas=1, min_available=1)
    job1.queue = "batch"
    admitted.add_batch_job(job1)
    converge(cm, sched, sim)
    assert store.batch_jobs["default/j1"].status.state.phase == "Running"

    store.add_command(Command(action="CloseQueue", target_kind="Queue",
                              target_name="batch"))
    cm.process()
    # j1's PodGroup still exists, so the queue drains through Closing
    # (queue_controller.go: Closed only when no PodGroups remain).
    assert store.raw_queues["batch"].state == "Closing"
    job2 = simple_job(name="j2", replicas=1, min_available=1)
    job2.queue = "batch"
    with pytest.raises(AdmissionError):
        admitted.add_batch_job(job2)
    # Running job unaffected.
    assert store.batch_jobs["default/j1"].status.state.phase == "Running"

    store.add_command(Command(action="OpenQueue", target_kind="Queue",
                              target_name="batch"))
    cm.process()
    assert store.raw_queues["batch"].state == "Open"
    admitted.add_batch_job(job2)
    converge(cm, sched, sim)
    assert store.batch_jobs["default/j2"].status.state.phase == "Running"


def test_sync_queue_compacts_stale_podgroups():
    """syncQueue's stale-member handling (the reference's NotFound
    branch, queue_controller_action.go:44-56; PARITY.md "Queue
    controller"): a PodGroup uid in the controller's per-queue index
    whose record is GONE from the store (the delete event raced or was
    lost — exactly the window the reference's "check NotFound error
    and sync local cache" comment covers) is deleted from the index
    during sync, the status counts exclude it, and the compaction
    sticks — a later sync sees the compacted membership."""
    from volcano_tpu.api import PodGroup, Queue
    from volcano_tpu.controllers import Command

    store, cm, sched, sim = make_env()
    store.add_queue(Queue(name="batch", weight=1))
    for i in range(3):
        store.add_pod_group(PodGroup(name=f"pg{i}", queue="batch"))
    qc = cm.queue_controller
    qc.process_all()
    assert qc.status["batch"].pending == 3
    assert qc._pg_list("batch") == {"default/pg0", "default/pg1",
                                    "default/pg2"}
    # Remove a PodGroup from the system of record WITHOUT the delete
    # event (pop the raw map, no _notify): the index now holds a
    # stale uid — the reference's informer-cache NotFound window.
    store.pod_groups.pop("default/pg1")
    store.add_command(Command(action="SyncQueue", target_kind="Queue",
                              target_name="batch"))
    cm.process()
    # The sync Get() missed -> local cache compacted, counts exclude
    # the stale member, queue state untouched (still Open).
    assert qc._pg_list("batch") == {"default/pg0", "default/pg2"}
    assert qc.status["batch"].pending == 2
    assert store.raw_queues["batch"].state == "Open"
    # The compaction is durable: a second sync re-counts the same.
    store.add_command(Command(action="SyncQueue", target_kind="Queue",
                              target_name="batch"))
    cm.process()
    assert qc.status["batch"].pending == 2


@pytest.mark.parametrize("event,action,expected_phase", [
    ("PodFailed", "RestartJob", "Running"),    # restarts back to Running
    ("PodFailed", "AbortJob", "Aborted"),
    ("PodFailed", "TerminateJob", "Terminated"),
    ("PodEvicted", "RestartJob", "Running"),
    ("PodEvicted", "AbortJob", "Aborted"),
    ("PodEvicted", "TerminateJob", "Terminated"),
    # RestartTask: declared in the reference's action enum and accepted
    # by admission, but its v0.4 controller leaves it to sync semantics
    # (actions.go:31 comment only, no state-machine arm) — the job stays
    # Running with the failed pod recorded; we match that.
    ("PodFailed", "RestartTask", "Running"),
])
def test_lifecycle_policy_event_action_matrix(event, action,
                                              expected_phase):
    """Event x Action lifecycle-policy matrix (job.go:129-156 +
    state FSM; the reference's job_error_handling.go e2e matrix)."""
    store, cm, sched, sim = make_env()
    job = simple_job(
        name="mx", replicas=2, min_available=2,
        policies=[LifecyclePolicy(event=event, action=action)],
    )
    store.add_batch_job(job)
    converge(cm, sched, sim)
    assert store.batch_jobs["default/mx"].status.state.phase == "Running"

    # Trigger the event on one pod.
    victim = next(p for p in store.pods.values()
                  if p.owner_job == "default/mx")
    if event == "PodFailed":
        sim.step(complete=lambda p: 1 if p.uid == victim.uid else None)
    else:  # PodEvicted
        from volcano_tpu.api import TaskInfo

        store.evict(TaskInfo(victim), "test eviction")
        sim.step()  # eviction completes (pod deleted)
    converge(cm, sched, sim, cycles=8)
    phase = store.batch_jobs["default/mx"].status.state.phase
    assert phase == expected_phase, (
        f"{event} x {action}: expected {expected_phase}, got {phase}"
    )
    if expected_phase == "Running" and action != "RestartTask":
        running = [p for p in store.pods.values()
                   if p.owner_job == "default/mx" and p.phase == "Running"]
        assert len(running) == 2
    if action == "RestartTask":
        # Sync semantics: the failure is recorded (not restarted).
        assert store.batch_jobs["default/mx"].status.failed == 1


def test_svc_network_policy_lifecycle():
    """svc creates a job-scoped ingress-isolation record (the
    NetworkPolicy of svc.go:252-299) and cleans it up with the job;
    --disable-network-policy suppresses it (svc.go:67)."""
    store, cm, sched, sim = make_env()
    job = Job(
        name="np",
        min_available=1,
        tasks=[TaskSpec(name="w", replicas=1,
                        containers=[{"cpu": "1", "memory": "1Gi"}])],
        plugins={"svc": []},
    )
    store.add_batch_job(job)
    converge(cm, sched, sim)
    pol = store.network_policies["default/np"]
    assert pol["pod_selector"]["volcano-tpu/job-name"] == "np"
    assert pol["ingress_from"] == [pol["pod_selector"]]
    assert pol["policy_types"] == ["Ingress"]

    store.delete_batch_job("default/np")
    converge(cm, sched, sim, cycles=6)
    assert "default/np" not in store.network_policies

    # Disabled via plugin argument.
    job2 = Job(
        name="np2",
        min_available=1,
        tasks=[TaskSpec(name="w", replicas=1,
                        containers=[{"cpu": "1", "memory": "1Gi"}])],
        plugins={"svc": ["--disable-network-policy"]},
    )
    store.add_batch_job(job2)
    converge(cm, sched, sim)
    assert "default/np2" not in store.network_policies


def test_job_volume_lifecycle():
    """Job with a VolumeClaim spec: the controller creates the claim at
    initiate (createJobIOIfNotExist, job_controller_actions.go:394-460),
    pods mount it, the scheduler allocates+binds it with the pod, and
    deleting the job reaps the controller-created claim (owner refs)."""
    from volcano_tpu.controllers import VolumeSpec

    store, cm, sched, sim = make_env()
    job = Job(
        name="vol",
        min_available=2,
        tasks=[TaskSpec(name="w", replicas=2,
                        containers=[{"cpu": "1", "memory": "1Gi"}])],
        volumes=[VolumeSpec(mount_path="/data",
                            volume_claim={"storage": "10Gi"})],
    )
    store.add_batch_job(job)
    converge(cm, sched, sim)

    # Claim generated + created + recorded in ControlledResources.
    gen_name = job.volumes[0].volume_claim_name
    assert gen_name.startswith("vol-volume-")
    assert f"volume-pvc-{gen_name}" in job.status.controlled_resources
    rec = store.pvcs[f"default/{gen_name}"]
    assert rec["owner_job"] == "default/vol"
    assert rec["spec"] == {"storage": "10Gi"}

    # Pods mount the claim and are bound; the claim bound with them, on
    # the node the scheduler picked.
    pods = [p for p in store.pods.values() if p.owner_job == "default/vol"]
    assert len(pods) == 2
    assert all(p.volumes == [(gen_name, "/data")] for p in pods)
    assert all(p.node_name for p in pods)
    assert rec["phase"] == "Bound"
    assert rec["node"] in {p.node_name for p in pods}

    # Job deletion reaps the owned claim.
    store.delete_batch_job("default/vol")
    converge(cm, sched, sim, cycles=6)
    assert f"default/{gen_name}" not in store.pvcs


def test_job_missing_named_claim_gates_pods():
    """A named claim that doesn't exist keeps the job Pending — no
    PodGroup, no pods — until the claim appears (the reference returns
    an error from initiateJob: 'pvc ... is not found, the job will be in
    the Pending state until the PVC is created')."""
    from volcano_tpu.controllers import VolumeSpec

    store, cm, sched, sim = make_env()
    job = Job(
        name="nv",
        min_available=1,
        tasks=[TaskSpec(name="w", replicas=1,
                        containers=[{"cpu": "1", "memory": "1Gi"}])],
        volumes=[VolumeSpec(mount_path="/data",
                            volume_claim_name="user-data")],
    )
    store.add_batch_job(job)
    converge(cm, sched, sim)
    assert "default/nv" not in store.pod_groups
    assert not [p for p in store.pods.values()
                if p.owner_job == "default/nv"]
    assert job.status.state.phase == JobPhase.Pending.value
    evs = store.events_for("Job/default/nv")
    assert any(e["reason"] == "PVCNotFound" for e in evs)

    # The user creates the claim: the job converges to Running.
    store.put_pvc("default", "user-data", {"storage": "5Gi"})
    converge(cm, sched, sim)
    pods = [p for p in store.pods.values() if p.owner_job == "default/nv"]
    assert pods and all(p.node_name for p in pods)
    assert store.pvcs["default/user-data"]["phase"] == "Bound"

    # Deleting the job must NOT reap a user-created claim (no owner ref).
    store.delete_batch_job("default/nv")
    converge(cm, sched, sim, cycles=6)
    assert "default/user-data" in store.pvcs


def test_volume_admission_rules():
    from volcano_tpu.controllers import VolumeSpec
    from volcano_tpu.webhooks.admission import (AdmissionError,
                                                validate_job_create)

    store, _, _, _ = make_env()

    def check(volumes, frag):
        job = Job(name="adm", min_available=1,
                  tasks=[TaskSpec(name="w", replicas=1,
                                  containers=[{"cpu": "1"}])],
                  volumes=volumes)
        with pytest.raises(AdmissionError) as ei:
            validate_job_create(job, store)
        assert frag in str(ei.value)

    check([VolumeSpec(mount_path="")], "mountPath is required")
    check([VolumeSpec(mount_path="/d", volume_claim={"storage": "1Gi"}),
           VolumeSpec(mount_path="/d", volume_claim={"storage": "1Gi"})],
          "duplicated mountPath")
    check([VolumeSpec(mount_path="/d")],
          "either volumeClaim or volumeClaimName")
    check([VolumeSpec(mount_path="/d", volume_claim_name="x",
                      volume_claim={"storage": "1Gi"})], "conflict")
    check([VolumeSpec(mount_path="/d", volume_claim_name="Bad_Name!")],
          "invalid volumeClaimName")
    # Valid spec admits.
    ok = Job(name="okv", min_available=1,
             tasks=[TaskSpec(name="w", replicas=1,
                             containers=[{"cpu": "1"}])],
             volumes=[VolumeSpec(mount_path="/d",
                                 volume_claim={"storage": "1Gi"})])
    validate_job_create(ok, store)


def test_vanished_controller_pvc_recreated():
    """A controller-created claim that vanishes (out-of-band delete /
    store restore) is recreated from the retained volumeClaim spec
    instead of wedging the job Pending."""
    from volcano_tpu.controllers import VolumeSpec

    store, cm, sched, sim = make_env()
    job = Job(
        name="rv",
        min_available=1,
        tasks=[TaskSpec(name="w", replicas=1,
                        containers=[{"cpu": "1", "memory": "1Gi"}])],
        volumes=[VolumeSpec(mount_path="/data",
                            volume_claim={"storage": "2Gi"})],
    )
    store.add_batch_job(job)
    converge(cm, sched, sim)
    name = job.volumes[0].volume_claim_name
    assert store.pvcs[f"default/{name}"]["phase"] == "Bound"

    store.delete_pvc("default", name)
    # Trigger a resync (scale keeps spec valid; any job event works).
    store.update_batch_job(job)
    converge(cm, sched, sim)
    rec = store.pvcs.get(f"default/{name}")
    assert rec is not None and rec["spec"] == {"storage": "2Gi"}


def test_invalid_volume_flags_job_not_phantom_claim():
    """Raw (admission-bypassing) submission with neither volumeClaim nor
    volumeClaimName: the job is flagged InvalidVolume and gated — no
    generated name, no misleading PVCNotFound."""
    from volcano_tpu.controllers import VolumeSpec

    store, cm, sched, sim = make_env()
    job = Job(
        name="iv",
        min_available=1,
        tasks=[TaskSpec(name="w", replicas=1,
                        containers=[{"cpu": "1", "memory": "1Gi"}])],
        volumes=[VolumeSpec(mount_path="/data")],
    )
    store.add_batch_job(job)
    converge(cm, sched, sim)
    assert not [p for p in store.pods.values()
                if p.owner_job == "default/iv"]
    assert job.volumes[0].volume_claim_name == ""
    evs = store.events_for("Job/default/iv")
    assert any(e["reason"] == "InvalidVolume" for e in evs)
    assert not any(e["reason"] == "PVCNotFound" for e in evs)
