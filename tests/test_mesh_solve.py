"""Mesh-native sharded wave solve (ISSUE 7): shard-local two-phase +
sharded devsnap deltas + pipelined mesh cycles.

What the mesh path must now guarantee on the virtual CPU mesh
(``xla_force_host_platform_device_count``, conftest — the same
decomposition runs unchanged on a real multi-chip TPU slice):

- the shard-local ranking + winner reduction (``ops.wave._topk_nodes``)
  is EXACTLY ``jax.lax.top_k`` including ties;
- the sharded solve is bind-for-bind identical to the single-device
  solve at fixed seeds, including shortlist-fallback and gang-atomicity
  cases (deterministic tie-breaks make this exact, not approximate);
- node churn under a mesh re-ships only dirty rows into the sharded
  devsnap planes (delta scatter), never the full plane set;
- pipelined dispatch works with ``store.solve_mesh`` set, and the
  staleness guard still drops rows invalidated during the overlap.

All tier-1, JAX_PLATFORMS=cpu.
"""

import numpy as np
import pytest

import jax

import volcano_tpu.ops.wave as wave
from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
)
from volcano_tpu.cache import ClusterStore
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.synth import solve_args_from_store, synthetic_cluster

pytestmark = pytest.mark.tier1

needs_4 = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs 4 virtual devices"
)


def _mesh(n=4):
    from volcano_tpu.parallel import make_mesh

    return make_mesh(n)


# ------------------------------------------------------- winner reduction


def test_topk_nodes_matches_global_topk():
    """The two-stage shard-local selection (per-shard top-k, then the
    (score, global node id) winner reduction) returns exactly what the
    global top_k returns — membership AND order, ties included."""
    rng = np.random.default_rng(7)
    for u, n, k, sh in [(5, 64, 7, 4), (3, 128, 128, 8), (2, 32, 10, 8),
                        (4, 16, 16, 4), (1, 256, 33, 4)]:
        # Small integer value set => heavy score ties across shards.
        s = rng.integers(0, 4, size=(u, n)).astype(np.float32)
        ref = np.asarray(jax.lax.top_k(s, k)[1])
        got = np.asarray(wave._topk_nodes(s, k, sh))
        assert np.array_equal(ref, got), (u, n, k, sh)
    # Degenerate: everything infeasible (all-NEG plane).
    s = np.full((3, 64), float(np.float32(-1e30)), np.float32)
    assert np.array_equal(
        np.asarray(jax.lax.top_k(s, 9)[1]),
        np.asarray(wave._topk_nodes(s, 9, 4)),
    )
    # Non-divisible node axis falls back to the global form.
    s = rng.normal(size=(2, 30)).astype(np.float32)
    assert np.array_equal(
        np.asarray(jax.lax.top_k(s, 5)[1]),
        np.asarray(wave._topk_nodes(s, 5, 4)),
    )


# --------------------------------------------------- solver-level parity


@needs_4
@pytest.mark.parametrize("shape", [
    dict(n_nodes=64, n_pods=128, gang_size=4, n_queues=2, seed=3),
    dict(n_nodes=32, n_pods=96, gang_size=4, zones=4,
         affinity_fraction=0.2, anti_affinity_fraction=0.1,
         spread_fraction=0.2, seed=5),
], ids=["plain", "affinity"])
def test_mesh_wave_solve_bind_for_bind(shape):
    """The sharded wave solve assigns every task the SAME node as the
    single-device solve (not just the same count): every cross-chip
    reduction is an exact-integer psum or a comparison, and the winner
    reduction carries global node ids for the tie-break."""
    from volcano_tpu.parallel import sharded_solve_wave

    args, _ = solve_args_from_store(synthetic_cluster(**shape))
    single = np.asarray(wave.solve_wave(*args).assigned)
    sharded = np.asarray(sharded_solve_wave(_mesh(4), args).assigned)
    assert np.array_equal(single, sharded)
    assert (single >= 0).any()


def _fallback_cluster():
    """12 identical nodes; the filler job's 8 single-node-sized pods
    saturate the shortlist prefix (identical nodes rank by index), so
    the gang of 4 binds only through the full-N fallback rescore —
    which under a mesh must run shard-local and reduce the same way."""
    store = ClusterStore()
    for i in range(12):
        store.add_node(Node(
            name=f"n{i:02d}", allocatable={"cpu": "4", "memory": "8Gi"}
        ))
    store.add_pod_group(PodGroup(name="filler", min_member=8))
    for r in range(8):
        store.add_pod(Pod(
            name=f"filler-{r}",
            annotations={GROUP_NAME_ANNOTATION: "filler"},
            containers=[{"cpu": "4", "memory": "8Gi"}],
        ))
    store.add_pod_group(PodGroup(name="gang", min_member=4))
    for r in range(4):
        store.add_pod(Pod(
            name=f"gang-{r}",
            annotations={GROUP_NAME_ANNOTATION: "gang"},
            containers=[{"cpu": "3", "memory": "6Gi"}],
        ))
    return store


@needs_4
def test_mesh_shortlist_fallback_parity(monkeypatch):
    """Shortlist exhaustion under sharding: the gang that binds only
    via the fallback rescore binds bind-for-bind like the single-device
    two-phase solve, the exhaustion is counted on both paths, and gang
    atomicity holds (all 12 pods bound)."""
    from volcano_tpu.parallel import sharded_solve_wave

    monkeypatch.setenv("VOLCANO_TPU_TOPK", "4")
    monkeypatch.setattr(wave, "TOPK", 4)
    monkeypatch.setenv("VOLCANO_TPU_TWOPHASE", "1")

    args, _ = solve_args_from_store(_fallback_cluster())
    single = wave.solve_wave(*args, wave=16)
    args2, _ = solve_args_from_store(_fallback_cluster())
    sharded = sharded_solve_wave(_mesh(4), args2, wave=16)

    a_single = np.asarray(single.assigned)
    a_mesh = np.asarray(sharded.assigned)
    assert np.array_equal(a_single, a_mesh)
    assert (a_mesh >= 0).sum() == 12  # gang atomic: everything bound
    assert int(np.asarray(sharded.fb_exhausted)) > 0
    assert int(np.asarray(sharded.fb_exhausted)) == int(
        np.asarray(single.fb_exhausted)
    )


# ------------------------------------------------- full-cycle parity


@needs_4
def test_mesh_full_cycle_bind_for_bind(monkeypatch):
    """Complete fastpath cycle on the mesh: every pod binds to the SAME
    node the single-device cycle picks (dict equality of the binder's
    pod -> hostname map), with the affinity mix exercising the sharded
    count tensors."""
    monkeypatch.setenv("VOLCANO_TPU_FALLBACK", "never")
    kw = dict(n_nodes=64, n_pods=128, gang_size=4, zones=4,
              affinity_fraction=0.25, anti_affinity_fraction=0.25,
              spread_fraction=0.25, seed=31)
    single = synthetic_cluster(**kw)
    Scheduler(single).run_once()
    single.flush_binds()

    meshed = synthetic_cluster(**kw)
    meshed.solve_mesh = _mesh(4)
    Scheduler(meshed).run_once()
    meshed.flush_binds()

    assert dict(meshed.binder.binds) == dict(single.binder.binds)
    assert len(meshed.binder.binds) == 128
    single.close()
    meshed.close()


# --------------------------------------------- sharded devsnap deltas


@needs_4
def test_mesh_devsnap_delta_after_node_churn(monkeypatch):
    """Node churn under the mesh re-ships only the dirty rows into the
    mesh-sharded persistent planes (delta scatter on the owning shard),
    NOT the full plane set — the re-upload carve-out the mesh path used
    to force is gone."""
    monkeypatch.setenv("VOLCANO_TPU_FALLBACK", "never")
    store = synthetic_cluster(seed=17, n_nodes=8, n_pods=16, gang_size=2)
    store.solve_mesh = _mesh(4)
    sched = Scheduler(store)
    sched.run_once()

    snap = store.device_snapshot
    assert snap.mesh is store.solve_mesh
    assert snap.full_uploads >= 1
    full_before = snap.full_uploads
    # Every persistent node plane is committed SHARDED on the node axis
    # (each chip holds its shard only).
    from jax.sharding import NamedSharding

    for name, plane in snap._planes.items():
        sh = plane.sharding
        assert isinstance(sh, NamedSharding), name
        assert sh.spec and sh.spec[0] == "nodes", name

    # One-node mutation: epoch bumps, one row dirty.
    store.add_node(Node(
        name="node-000000",
        allocatable={"cpu": "64", "memory": "256Gi", "pods": 256},
        labels={"freshly": "relabelled"},
    ))
    store.add_pod_group(PodGroup(name="late", min_member=1))
    store.add_pod(Pod(
        name="late-0",
        annotations={GROUP_NAME_ANNOTATION: "late"},
        containers=[{"cpu": "1", "memory": "1Gi"}],
    ))
    sched.run_once()
    store.flush_binds()
    assert snap.delta_uploads >= 1, "churn must ride the delta scatter"
    assert snap.full_uploads == full_before, \
        "node churn must not full-re-upload the sharded planes"
    assert all(p.node_name for p in store.pods.values())
    store.close()


# -------------------------------------------------- pipelined mesh


@needs_4
def test_mesh_pipelined_cycle_commits(monkeypatch):
    """Pipelined dispatch with ``solve_mesh`` set: cycle N parks the
    sharded solve as an InflightSolve, cycle N+1 fetches (one
    jax.device_get assembling the mesh result) and commits."""
    monkeypatch.setenv("VOLCANO_TPU_FALLBACK", "never")
    store = synthetic_cluster(seed=23, n_nodes=16, n_pods=32, gang_size=2)
    store.pipeline = True
    store.solve_mesh = _mesh(4)
    sched = Scheduler(store)
    sched.run_once()
    # The solve is parked, not committed: pipelining engaged on the mesh.
    assert store._inflight_solve is not None
    assert store._inflight_solve.kind == "local"
    sched.run_once()
    store.flush_binds()
    assert len(store.binder.binds) == 32
    store.close()


@needs_4
def test_mesh_pipelined_staleness_guard_drops_deleted(monkeypatch):
    """A pod deleted while its sharded solve is in flight must NOT be
    committed: the staleness guard re-validates the mesh result exactly
    like the single-device one."""
    monkeypatch.setenv("VOLCANO_TPU_FALLBACK", "never")
    store = synthetic_cluster(seed=29, n_nodes=16, n_pods=32, gang_size=1)
    store.pipeline = True
    store.solve_mesh = _mesh(4)
    sched = Scheduler(store)
    sched.run_once()
    assert store._inflight_solve is not None

    victim = next(p for p in store.pods.values()
                  if p.node_name is None)
    store.delete_pod(victim)
    sched.run_once()
    sched.run_once()
    store.flush_binds()
    key = f"{victim.namespace}/{victim.name}"
    assert key not in store.binder.binds
    assert len(store.binder.binds) == 31  # everyone else lands
    store.close()
