"""Randomized invariant sweep (SURVEY.md 4.3: property tests — gang
atomicity, no oversubscription) over seeded synthetic clusters.

Each seed draws a different cluster shape/gang mix; invariants are checked
from the store after a full cycle, independent of the solver's internals.
"""

import numpy as np
import pytest

from volcano_tpu.api.resource import Resource
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.synth import synthetic_cluster

CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


def _shape(seed):
    rng = np.random.RandomState(seed)
    return dict(
        n_nodes=int(rng.randint(4, 24)),
        n_pods=int(rng.randint(8, 120)),
        gang_size=int(rng.choice([1, 2, 3, 5, 8])),
        n_queues=int(rng.choice([1, 2, 3])),
        zones=int(rng.choice([0, 2, 4])),
        affinity_fraction=float(rng.choice([0.0, 0.2])),
        anti_affinity_fraction=float(rng.choice([0.0, 0.2])),
        spread_fraction=float(rng.choice([0.0, 0.3])),
        seed=seed,
    )


@pytest.mark.parametrize("seed", range(8))
def test_cycle_invariants(seed):
    kw = _shape(seed)
    store = synthetic_cluster(**kw)
    Scheduler(store, conf_str=CONF).run_once()

    # --- no oversubscription: per-node bound requests fit allocatable ---
    node_alloc = {}
    node_used = {}
    for name, ninfo in store.nodes.items():
        node_alloc[name] = ninfo.node.allocatable_resource()
        node_used[name] = Resource()
    per_job_bound = {}
    for pod in store.pods.values():
        if pod.node_name:
            req = Resource()
            for c in pod.containers:
                req.add(Resource.from_resource_list(c))
            node_used[pod.node_name].add(req)
        gid = pod.job_id()
        if gid:
            per_job_bound.setdefault(gid, [0, 0])
            per_job_bound[gid][1] += 1
            if pod.node_name:
                per_job_bound[gid][0] += 1
    for name, used in node_used.items():
        assert used.less_equal(node_alloc[name]), (
            f"node {name} oversubscribed: {used} > {node_alloc[name]}"
        )

    # --- gang atomicity: a gang binds fully-to-min or not at all -------
    for group, (bound, total) in per_job_bound.items():
        pg = store.pod_groups.get(group)
        if pg is None:
            continue
        assert bound == 0 or bound >= pg.min_member, (
            f"gang {group} partially bound: {bound}/{total} "
            f"(min {pg.min_member})"
        )

    # --- binds only on known nodes -------------------------------------
    for pod in store.pods.values():
        if pod.node_name:
            assert pod.node_name in node_alloc
