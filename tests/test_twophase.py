"""Two-phase device solve (ISSUE 4): node-class compaction +
per-profile top-K shortlists.

Pins what the hierarchical solve must guarantee against the full-``N``
single-phase solve it replaces:

- bind-for-bind parity on fixed seeds at configs-2/3/5-like shapes with
  the shortlist genuinely restrictive (K << N), including the affinity
  mix and a gang that can only bind through the fallback rescore;
- capacity + gang atomicity under shortlist exhaustion;
- fallback counters exported per reason and consistent with the binds;
- the compacted fine-phase planes really are [U, K] with K << N;
- devsnap class-plane delta correctness after node mutations.

All tier-1, JAX_PLATFORMS=cpu.
"""

import numpy as np
import pytest

import volcano_tpu.ops.wave as wave
from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
)
from volcano_tpu.cache import ClusterStore
from volcano_tpu.metrics import metrics
from volcano_tpu.synth import solve_args_from_store, synthetic_cluster

pytestmark = pytest.mark.tier1


def _pin(monkeypatch, k, twophase):
    """Pin the shortlist length AND the walk ranking depth to ``k`` for
    BOTH modes (the module-level TOPK is read at import, the shortlist
    length per call), so parity compares identical walk depths."""
    monkeypatch.setenv("VOLCANO_TPU_TOPK", str(k))
    monkeypatch.setattr(wave, "TOPK", k)
    monkeypatch.setenv("VOLCANO_TPU_TWOPHASE", "1" if twophase else "0")


def _solve(store, wave_sz=64):
    args, _ = solve_args_from_store(store)
    res = wave.solve_wave(*args, wave=wave_sz)
    return args, res


def _assigned(res):
    return np.asarray(res.assigned)


def _fb(res):
    return (int(np.asarray(res.fb_exhausted)),
            int(np.asarray(res.fb_affinity)))


def _check_invariants(args, res):
    nodes, tasks, jobs = args[0], args[1], args[2]
    assigned = _assigned(res)
    idle0 = np.asarray(nodes.idle)
    req = np.asarray(tasks.req)
    use = np.zeros_like(idle0)
    for i, n in enumerate(assigned):
        if n >= 0:
            use[n] += req[i]
    assert (use <= idle0 + 1e-3).all(), "node oversubscription"
    job = np.asarray(tasks.job)
    real = np.asarray(tasks.real)
    minav = np.asarray(jobs.min_available)
    rb = np.asarray(jobs.ready_base)
    counts = {}
    for i in range(len(assigned)):
        if real[i] and assigned[i] >= 0:
            counts[job[i]] = counts.get(job[i], 0) + 1
    for j, c in counts.items():
        assert rb[j] + c >= minav[j], "gang atomicity violated"
    never = np.asarray(res.never_ready)
    for i in range(len(assigned)):
        if real[i] and never[job[i]]:
            assert assigned[i] == -1, "discarded job left an allocation"


# --------------------------------------------------------------- parity


PARITY_SHAPES = [
    # config-2-like: binpack+predicates, single-queue-ish
    ("cfg2", 12, dict(n_nodes=48, n_pods=160, gang_size=4, n_queues=2,
                      seed=3)),
    # config-3-like: weighted multi-queue DRF mix
    ("cfg3", 16, dict(n_nodes=48, n_pods=128, n_queues=4,
                      queue_weights=(1, 2, 4, 8),
                      gang_sizes=(2, 4, 8, 16), seed=5)),
    # config-5-like: inter-pod affinity / anti-affinity / spread mix
    ("cfg5", 16, dict(n_nodes=32, n_pods=96, gang_size=4, zones=4,
                      affinity_fraction=0.2, anti_affinity_fraction=0.1,
                      spread_fraction=0.2, seed=3)),
]


@pytest.mark.parametrize("name,k,shape",
                         PARITY_SHAPES, ids=[s[0] for s in PARITY_SHAPES])
def test_twophase_bind_for_bind_parity(monkeypatch, name, k, shape):
    """Fixed-seed parity: with the shortlist restricted to K << N, the
    two-phase solve binds the same pods to the same nodes as the full
    solve (same walk depth in both modes)."""
    _pin(monkeypatch, k, twophase=False)
    _, full = _solve(synthetic_cluster(**shape))
    _pin(monkeypatch, k, twophase=True)
    args, two = _solve(synthetic_cluster(**shape))
    assert wave.LAST_TWOPHASE["enabled"]
    assert np.array_equal(_assigned(full), _assigned(two))
    _check_invariants(args, two)
    # Fallback counters always export (zeros allowed on shapes where
    # nothing exhausts).
    ex, aff = _fb(two)
    assert ex >= 0 and aff >= 0


def test_twophase_shortlist_planes_are_compacted(monkeypatch):
    """The fine-phase candidate planes are [U, K] with K << N."""
    _pin(monkeypatch, 8, twophase=True)
    store = synthetic_cluster(n_nodes=64, n_pods=128, gang_size=4, seed=1)
    _, res = _solve(store)
    info = wave.LAST_TWOPHASE
    assert info["enabled"] and info["compacted_classes"]
    u_rows, s = info["shortlist"]
    n = info["n_nodes"]
    assert s == 8 and n == 64 and s < n // 4
    assert u_rows >= 1
    assert (_assigned(res) >= 0).sum() == 128


def _fallback_cluster():
    """12 identical nodes; job A's 8 single-node-sized pods saturate the
    shortlist prefix (identical nodes rank by index), so job B's gang of
    4 can only bind through the full-N fallback rescore."""
    store = ClusterStore()
    for i in range(12):
        store.add_node(Node(
            name=f"n{i:02d}", allocatable={"cpu": "4", "memory": "8Gi"}
        ))
    store.add_pod_group(PodGroup(name="filler", min_member=8))
    for r in range(8):
        store.add_pod(Pod(
            name=f"filler-{r}",
            annotations={GROUP_NAME_ANNOTATION: "filler"},
            containers=[{"cpu": "4", "memory": "8Gi"}],
        ))
    store.add_pod_group(PodGroup(name="gang", min_member=4))
    for r in range(4):
        store.add_pod(Pod(
            name=f"gang-{r}",
            annotations={GROUP_NAME_ANNOTATION: "gang"},
            containers=[{"cpu": "3", "memory": "6Gi"}],
        ))
    return store


def test_twophase_gang_binds_only_via_fallback(monkeypatch):
    """A gang whose shortlist is fully claimed by earlier waves still
    binds (fallback full-N rescore), bind-for-bind equal to the full
    solve, with the exhaustion counted and exported."""
    _pin(monkeypatch, 4, twophase=False)
    _, full = _solve(_fallback_cluster(), wave_sz=16)
    _pin(monkeypatch, 4, twophase=True)
    args, two = _solve(_fallback_cluster(), wave_sz=16)
    assert np.array_equal(_assigned(full), _assigned(two))
    assert (_assigned(two) >= 0).sum() == 12  # all 12 pods bound
    ex, aff = _fb(two)
    assert ex > 0, "shortlist exhaustion must be counted"
    assert aff == 0
    _check_invariants(args, two)


def test_twophase_exhaustion_keeps_capacity_and_gang_atomicity(
        monkeypatch):
    """Overcommitted cluster + tiny shortlist: whatever binds must still
    respect capacity and gang atomicity, and unbindable gangs discard
    cleanly (capacity restored)."""
    _pin(monkeypatch, 4, twophase=True)
    store = synthetic_cluster(n_nodes=24, n_pods=256, gang_size=8,
                              n_queues=2, seed=11)
    args, res = _solve(store)
    _check_invariants(args, res)
    # Parity of *placement count* with the full solve under the same
    # pressure (identical walk depth).
    _pin(monkeypatch, 4, twophase=False)
    _, full = _solve(synthetic_cluster(n_nodes=24, n_pods=256,
                                       gang_size=8, n_queues=2, seed=11))
    assert (_assigned(res) >= 0).sum() == (_assigned(full) >= 0).sum()


def test_fallback_cap_limits_rescores(monkeypatch):
    """VOLCANO_TPU_FB_CAP bounds the fallback rescore ROUNDS; past the
    cap exhausted profiles stay Pending (the sampling-cutoff
    semantics) — and the cap never breaks capacity/gang invariants."""
    _pin(monkeypatch, 4, twophase=True)
    monkeypatch.setenv("VOLCANO_TPU_FB_CAP", "0")
    _, uncapped = _solve(_fallback_cluster(), wave_sz=16)
    monkeypatch.setenv("VOLCANO_TPU_FB_CAP", "1")
    args, res = _solve(_fallback_cluster(), wave_sz=16)
    ex, aff = _fb(res)
    ex_unc, _aff_unc = _fb(uncapped)
    # One round fired (both profiles of that attempt rescored), later
    # exhaustions were refused: fewer rescored profiles than uncapped,
    # and the gang that needed a later round stays Pending.
    assert 0 < ex + aff < ex_unc
    assert (_assigned(res) >= 0).sum() < (_assigned(uncapped) >= 0).sum()
    _check_invariants(args, res)


# ------------------------------------------------- metrics + scheduler


def test_fallback_counter_exported_via_scheduler(monkeypatch):
    """Driving the full fast path: the per-reason counter series and the
    per-store accumulator pick up the kernel's fallback counts."""
    from volcano_tpu.scheduler import Scheduler

    _pin(monkeypatch, 4, twophase=True)

    def series_total():
        data = metrics.solve_shortlist_fallback.data
        return sum(data.values())

    before = series_total()
    store = _fallback_cluster()
    Scheduler(store).run_once()
    store.flush_binds()
    assert all(p.node_name for p in store.pods.values())
    delta = series_total() - before
    acc = getattr(store, "_shortlist_fb", {})
    assert delta > 0
    assert sum(acc.values()) == delta


# --------------------------------------------- devsnap class planes


def test_devsnap_class_planes_delta_after_node_mutation(monkeypatch):
    """Node mutations between cycles: a label change that alters the
    class SET re-uploads the class_id plane + tables but keeps the
    other node planes on the delta path, and the post-mutation solve
    matches a fresh store with the same final state bind-for-bind."""
    from volcano_tpu.scheduler import Scheduler

    _pin(monkeypatch, 8, twophase=True)
    store = synthetic_cluster(n_nodes=8, n_pods=16, gang_size=2, seed=17)
    sched = Scheduler(store)
    sched.run_once()
    snap = store.device_snapshot
    assert snap.class_uploads >= 1
    full_before = snap.full_uploads
    cls_uploads_before = snap.class_uploads

    # Mutate one node's labels -> new class signature set.
    store.add_node(Node(
        name="node-000000",
        allocatable={"cpu": "64", "memory": "256Gi", "pods": 256},
        labels={"pool": "relabelled"},
    ))
    store.add_pod_group(PodGroup(name="late", min_member=1))
    store.add_pod(Pod(
        name="late-0",
        annotations={GROUP_NAME_ANNOTATION: "late"},
        node_selector={"pool": "relabelled"},
        containers=[{"cpu": "1", "memory": "1Gi"}],
    ))
    sched.run_once()
    store.flush_binds()
    # The class tables re-uploaded (new signature set), the node planes
    # did NOT take the full path (label delta scatters still apply).
    assert snap.class_uploads > cls_uploads_before
    assert snap.full_uploads == full_before
    assert snap.delta_uploads >= 1
    # The selector-pinned pod landed on the relabelled node: the
    # device-resident class planes really reflect the mutation.
    late = [p for p in store.pods.values() if p.name == "late-0"]
    assert late and late[0].node_name == "node-000000"