"""Device-kernel tests: fit semantics vs the host oracle, scoring math, and
the allocate solver's gang/pipeline/overuse semantics.

Follows the reference's action-test pattern
(pkg/scheduler/actions/allocate/allocate_test.go:155-222): build a cluster
through the store, run the solver, assert the assignment.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    PodPhase,
    Queue,
    Resource,
    TaskStatus,
)
from volcano_tpu.arrays import encode_affinity, encode_cluster
from volcano_tpu.cache import ClusterStore
from volcano_tpu.ops import (
    default_weights,
    less_equal,
    solve,
    solve_inputs,
    static_predicate_mask,
)
from volcano_tpu.ops.scoring import binpack_score, ScoreWeights


# ---------------------------------------------------------------- fit kernel


def test_less_equal_matches_host_oracle():
    rng = np.random.default_rng(42)
    eps = np.array([10.0, 10 * 1024 * 1024, 10.0], np.float32)
    scalar = np.array([False, False, True])
    for _ in range(200):
        l = rng.choice(
            [0.0, 5.0, 9.999, 10.0, 1000.0, 1009.0, 1010.0, 2.5e7], size=3
        )
        r = rng.choice([0.0, 5.0, 10.0, 1000.0, 1005.0, 2.0e7, 3.0e7], size=3)
        host_l = Resource(l[0], l[1], {"g": l[2]} if l[2] else None)
        host_r = Resource(r[0], r[1], {"g": r[2]} if r[2] else None)
        got = bool(
            less_equal(
                jnp.asarray(l, jnp.float32), jnp.asarray(r, jnp.float32),
                jnp.asarray(eps), jnp.asarray(scalar),
            )
        )
        want = host_l.less_equal(host_r)
        assert got == want, f"l={l} r={r}: device={got} host={want}"


def test_binpack_score_math():
    # binpack.go:248-259: score_r = (used+req)*w/cap; 0 if over capacity.
    w = ScoreWeights(
        binpack_weight=1.0,
        binpack_res=jnp.array([1.0, 1.0], jnp.float32),
        least_req_weight=0.0,
        most_req_weight=0.0,
        balanced_weight=0.0,
        node_affinity_weight=0.0,
    )
    req = jnp.array([1000.0, 0.0], jnp.float32)  # cpu-only request
    allocatable = jnp.array([[4000.0, 8.0], [2000.0, 8.0]], jnp.float32)
    used = jnp.array([[1000.0, 0.0], [1500.0, 0.0]], jnp.float32)
    s = binpack_score(req, allocatable, used, w)
    # node0: (1000+1000)/4000 * 1 / 1 * 10 = 5.0
    assert float(s[0]) == pytest.approx(5.0)
    # node1: (1500+1000)=2500 > 2000 -> 0
    assert float(s[1]) == pytest.approx(0.0)


# ------------------------------------------------------------ solver harness


def build_store(nodes, groups):
    """nodes: [(name, cpu, mem)], groups: [(pg_name, min_member, queue,
    [(pod_name, cpu, mem)])]"""
    store = ClusterStore()
    for name, cpu, mem in nodes:
        store.add_node(Node(name=name, allocatable={"cpu": cpu, "memory": mem}))
    for pg_name, min_member, queue, pods in groups:
        if queue != "default" and queue not in store.queues:
            store.add_queue(Queue(name=queue, weight=1))
        store.add_pod_group(
            PodGroup(name=pg_name, min_member=min_member, queue=queue)
        )
        for pod_name, cpu, mem in pods:
            store.add_pod(
                Pod(
                    name=pod_name,
                    annotations={GROUP_NAME_ANNOTATION: pg_name},
                    containers=[{"cpu": cpu, "memory": mem}],
                )
            )
    return store


def run_solver(store, job_ids=None, pending=None, weights=None,
               task_key=None):
    """Encode + solve; the single spelling of the 22-arg solve call."""
    snap = store.snapshot()
    job_ids = job_ids or sorted(snap.jobs.keys())
    if pending is None:
        key = task_key or (
            lambda t: (-t.priority, t.pod.creation_timestamp)
        )
        pending = []
        for jid in job_ids:
            job = snap.jobs[jid]
            tasks = sorted(
                job.task_status_index.get(TaskStatus.Pending, {}).values(),
                key=key,
            )
            pending.extend(t for t in tasks if not t.resreq.is_empty())
    arrays, maps = encode_cluster(snap, pending, job_ids)
    s_nodes, s_tasks, s_jobs, s_queues = solve_inputs(arrays)
    res = solve(
        s_nodes, s_tasks, s_jobs, s_queues,
        weights if weights is not None else default_weights(maps.slots.width),
        arrays.eps,
        arrays.scalar_slot,
        encode_affinity(snap, pending, maps.node_names,
                        arrays.nodes.idle.shape[0],
                        arrays.tasks.req.shape[0]),
    )
    return res, maps


def assignments(res, maps):
    out = {}
    for i, uid in enumerate(maps.task_uids):
        n = int(res.assigned[i])
        ti = maps.task_infos[i]
        out[ti.name] = maps.node_names[n] if n >= 0 else None
    return out


# ---------------------------------------------------------------- scenarios


def test_gang_fits_all_assigned():
    store = build_store(
        nodes=[("n1", "4", "8Gi"), ("n2", "4", "8Gi")],
        groups=[("pg1", 3, "default",
                 [("p0", "2", "2Gi"), ("p1", "2", "2Gi"), ("p2", "2", "2Gi")])],
    )
    res, maps = run_solver(store)
    a = assignments(res, maps)
    assert all(v is not None for v in a.values()), a
    assert not bool(res.never_ready[0])
    # No node oversubscribed: 2 tasks max per 4-cpu node.
    counts = {}
    for v in a.values():
        counts[v] = counts.get(v, 0) + 1
    assert max(counts.values()) <= 2


def test_gang_insufficient_discards_all():
    # min_member=3 but only 2 tasks fit cluster-wide -> zero assignments.
    store = build_store(
        nodes=[("n1", "4", "8Gi")],
        groups=[("pg1", 3, "default",
                 [("p0", "2", "2Gi"), ("p1", "2", "2Gi"), ("p2", "2", "2Gi")])],
    )
    res, maps = run_solver(store)
    a = assignments(res, maps)
    assert all(v is None for v in a.values()), a
    assert bool(res.never_ready[0])
    # Capacity restored: final idle == initial.
    assert float(res.idle[0, 0]) == 4000.0


def test_gang_discard_frees_capacity_for_next_job():
    # Failed gang must not consume capacity needed by a later job.
    store = build_store(
        nodes=[("n1", "4", "8Gi")],
        groups=[
            ("pga", 3, "default",
             [("a0", "2", "1Gi"), ("a1", "2", "1Gi"), ("a2", "2", "1Gi")]),
            ("pgb", 2, "default", [("b0", "2", "1Gi"), ("b1", "2", "1Gi")]),
        ],
    )
    res, maps = run_solver(store, job_ids=["default/pga", "default/pgb"])
    a = assignments(res, maps)
    assert a["a0"] is None and a["a1"] is None and a["a2"] is None
    assert a["b0"] == "n1" and a["b1"] == "n1"


def test_partial_gang_min_available_less_than_replicas():
    # 3 replicas, min_member=2, capacity for 2 -> exactly 2 assigned.
    store = build_store(
        nodes=[("n1", "4", "8Gi")],
        groups=[("pg1", 2, "default",
                 [("p0", "2", "2Gi"), ("p1", "2", "2Gi"), ("p2", "2", "2Gi")])],
    )
    res, maps = run_solver(store)
    a = assignments(res, maps)
    placed = [k for k, v in a.items() if v is not None]
    assert len(placed) == 2
    assert not bool(res.never_ready[0])


def test_no_oversubscription_two_jobs():
    store = build_store(
        nodes=[("n1", "2", "4Gi"), ("n2", "2", "4Gi")],
        groups=[
            ("pg1", 1, "default", [("p0", "2", "1Gi")]),
            ("pg2", 1, "default", [("q0", "2", "1Gi")]),
        ],
    )
    res, maps = run_solver(store, job_ids=["default/pg1", "default/pg2"])
    a = assignments(res, maps)
    assert a["p0"] is not None and a["q0"] is not None
    assert a["p0"] != a["q0"]  # each node has cpu for only one


def test_pipeline_on_releasing_resources():
    # Node full but with a releasing task: pending task gets pipelined,
    # not allocated (allocate.go:224-232).
    store = ClusterStore()
    store.add_node(Node(name="n1", allocatable={"cpu": "2", "memory": "4Gi"}))
    store.add_pod_group(PodGroup(name="old", min_member=1))
    victim = Pod(
        name="v0",
        annotations={GROUP_NAME_ANNOTATION: "old"},
        containers=[{"cpu": "2", "memory": "1Gi"}],
        phase=PodPhase.Running,
        node_name="n1",
    )
    store.add_pod(victim)
    # Evict it -> releasing.
    vt = next(iter(store.jobs["default/old"].tasks.values()))
    store.evict(vt, "test")
    store.add_pod_group(PodGroup(name="new", min_member=1))
    store.add_pod(
        Pod(
            name="p0",
            annotations={GROUP_NAME_ANNOTATION: "new"},
            containers=[{"cpu": "2", "memory": "1Gi"}],
        )
    )
    res, maps = run_solver(store, job_ids=["default/new"])
    assert int(res.assigned[0]) == -1
    assert int(res.pipelined[0]) == maps.node_index["n1"]


def test_fit_failure_aborts_rest_of_job():
    # p0 fits; p1 requests more than any node has -> no feasible node;
    # p2 would fit but must not be attempted (allocate.go:189-193);
    # job min=2 never ready -> all discarded.
    store = build_store(
        nodes=[("n1", "4", "8Gi")],
        groups=[("pg1", 2, "default",
                 [("p0", "1", "1Gi"), ("p1", "100", "1Gi"), ("p2", "1", "1Gi")])],
    )
    res, maps = run_solver(store, task_key=lambda t: t.name)
    assert bool(res.fit_failed[0])
    assert bool(res.never_ready[0])
    assert all(int(x) == -1 for x in res.assigned[:3])
    assert float(res.idle[0, 0]) == 4000.0


def test_node_selector_respected():
    store = ClusterStore()
    store.add_node(Node(name="n1", allocatable={"cpu": "4", "memory": "8Gi"},
                        labels={"zone": "a"}))
    store.add_node(Node(name="n2", allocatable={"cpu": "4", "memory": "8Gi"},
                        labels={"zone": "b"}))
    store.add_pod_group(PodGroup(name="pg1", min_member=1))
    store.add_pod(
        Pod(
            name="p0",
            annotations={GROUP_NAME_ANNOTATION: "pg1"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
            node_selector={"zone": "b"},
        )
    )
    res, maps = run_solver(store)
    a = assignments(res, maps)
    assert a["p0"] == "n2"


def test_binpack_prefers_used_node():
    # With binpack enabled, the second task should pack onto the node that
    # already hosts the first.
    store = build_store(
        nodes=[("n1", "8", "16Gi"), ("n2", "8", "16Gi")],
        groups=[("pg1", 2, "default", [("p0", "1", "1Gi"), ("p1", "1", "1Gi")])],
    )
    res, maps = run_solver(
        store,
        task_key=lambda t: t.name,
        weights=default_weights(2, binpack_enabled=True,
                                nodeorder_enabled=False),
    )
    a = {maps.task_infos[i].name: int(res.assigned[i]) for i in range(2)}
    assert a["p0"] == a["p1"]


def test_less_matches_host_oracle():
    from volcano_tpu.ops import less

    rng = np.random.default_rng(7)
    eps = np.array([10.0, 10 * 1024 * 1024, 10.0], np.float32)
    scalar = np.array([False, False, True])
    for _ in range(200):
        l = rng.choice([0.0, 5.0, 100.0, 1000.0, 2.0e7], size=3)
        r = rng.choice([0.0, 5.0, 10.0, 101.0, 1000.0, 3.0e7], size=3)
        host_l = Resource(l[0], l[1], {"g": l[2]} if l[2] else None)
        host_r = Resource(r[0], r[1], {"g": r[2]} if r[2] else None)
        got = bool(
            less(jnp.asarray(l, jnp.float32), jnp.asarray(r, jnp.float32),
                 jnp.asarray(eps), jnp.asarray(scalar))
        )
        want = host_l.less(host_r)
        assert got == want, f"l={l} r={r}: device={got} host={want}"


def test_overused_skip_not_reported_as_gang_discard():
    # A job skipped for queue overuse must not be flagged never_ready.
    store = build_store(
        nodes=[("n1", "8", "16Gi")],
        groups=[("pg1", 1, "default", [("p0", "1", "1Gi")])],
    )
    snap = store.snapshot()
    job = snap.jobs["default/pg1"]
    pending = sorted(
        job.task_status_index[TaskStatus.Pending].values(), key=lambda t: t.name
    )
    arrays, maps = encode_cluster(snap, pending, ["default/pg1"])
    Q, R = arrays.queues.capability.shape
    # deserved = 0 -> queue overused only when allocation > epsilon; force
    # overuse by pre-charging the queue allocation.
    deserved = np.zeros((Q, R), np.float32)
    q_alloc0 = np.full((Q, R), 1.0e9, np.float32)
    s_nodes, s_tasks, s_jobs, s_queues = solve_inputs(
        arrays, deserved, q_alloc0
    )
    res = solve(
        s_nodes, s_tasks, s_jobs, s_queues,
        default_weights(maps.slots.width), arrays.eps, arrays.scalar_slot,
        encode_affinity(snap, pending, maps.node_names,
                        arrays.nodes.idle.shape[0],
                        arrays.tasks.req.shape[0]),
    )
    assert int(res.assigned[0]) == -1  # skipped
    assert not bool(res.never_ready[0])  # but not reported as gang discard
    assert not bool(res.fit_failed[0])
