"""Mirror maintenance: compaction, dynamic updates, fallback eligibility."""

import os

import numpy as np

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
)
from volcano_tpu.cache import ClusterStore
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.synth import synthetic_cluster


def test_compaction_preserves_scheduling():
    """Deleting >half the pod table triggers compaction; scheduling after
    compaction matches a fresh store with the same surviving state."""
    store = ClusterStore()
    for i in range(4):
        store.add_node(Node(name=f"n{i}",
                            allocatable={"cpu": "8", "memory": "16Gi"}))
    # Churn: add and delete enough pods to cross the compaction threshold.
    dead = []
    for i in range(5000):
        p = Pod(name=f"tmp-{i}", containers=[{"cpu": "100m",
                                              "memory": "64Mi"}])
        store.add_pod(p)
        dead.append(p)
    for p in dead:
        store.delete_pod(p)
    assert store.mirror.n_dead == 0 or store.mirror.n_pods < 5000
    # Survivors scheduled after compaction.
    store.add_pod_group(PodGroup(name="g", min_member=3))
    for i in range(3):
        store.add_pod(Pod(name=f"w{i}",
                          containers=[{"cpu": "1", "memory": "1Gi"}],
                          annotations={GROUP_NAME_ANNOTATION: "g"}))
    Scheduler(store).run_once()
    assert len(store.binder.binds) == 3


def test_custom_plugin_conf_falls_back_to_object_path():
    """Non-built-in plugin names make the fast path ineligible; the object
    session handles the cycle and still binds."""
    conf = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: gang
  - name: priority
  - name: conformance
"""
    # Sanity: this conf IS eligible; now an unknown plugin is not.
    conf_custom = conf + "  - name: my-custom-plugin\n"
    import volcano_tpu.fastpath as fp
    from volcano_tpu.framework import parse_scheduler_conf

    store = synthetic_cluster(n_nodes=4, n_pods=8, gang_size=2)
    assert fp.FastCycle(store, parse_scheduler_conf(conf)).eligible()
    parsed = parse_scheduler_conf(conf_custom)
    assert not fp.FastCycle(store, parsed).eligible()
    Scheduler(store, conf_str=conf_custom).run_once()
    assert len(store.binder.binds) == 8


def test_mirror_tracks_bind_and_evict_status():
    from volcano_tpu.api import TaskStatus

    store = synthetic_cluster(n_nodes=4, n_pods=8, gang_size=2)
    Scheduler(store).run_once()
    m = store.mirror
    bound_rows = np.flatnonzero(
        m.p_status[:m.n_pods] == int(TaskStatus.Bound)
    )
    assert len(bound_rows) == 8
    # Evict one pod through the store; mirror follows.
    pod = next(iter(store.pods.values()))
    ti = store.jobs[pod.job_id()].tasks[pod.uid]
    store.evict(ti, "test")
    row = m.p_row[pod.uid]
    assert m.p_status[row] == int(TaskStatus.Releasing)


def test_checkpoint_then_schedule_more(tmp_path):
    """A restored store keeps scheduling new work (mirror rebuilt via the
    event API replay)."""
    from volcano_tpu.persistence import load_store, save_store

    store = synthetic_cluster(n_nodes=4, n_pods=8, gang_size=2)
    Scheduler(store).run_once()
    path = str(tmp_path / "ckpt")
    save_store(store, path)
    b = load_store(path)
    b.add_pod_group(PodGroup(name="late", min_member=2))
    for i in range(2):
        b.add_pod(Pod(name=f"late-{i}",
                      containers=[{"cpu": "1", "memory": "1Gi"}],
                      annotations={GROUP_NAME_ANNOTATION: "late"}))
    Scheduler(b).run_once()
    assert any(k.endswith("late-0") for k in b.binder.binds)


def test_object_path_status_writes_refresh_mirror_columns():
    """update_job_status / record_job_condition (the object session's
    write-back) must re-sync the mirror's persistent j_phase_code /
    j_st_* / j_cond_sig columns, or the fast path's change detection
    works off stale 'last written' state after a slow-path cycle."""
    from volcano_tpu.api import PodGroup, PodGroupCondition
    from volcano_tpu.cache import ClusterStore

    store = ClusterStore()
    pg = PodGroup(name="g", min_member=2)
    store.add_pod_group(pg)
    m = store.mirror
    row = m.j_row[pg.uid]
    assert m.j_phase_code[row] == 1  # Pending

    # Object-path write-back: phase + counters via update_job_status.
    snap = store.snapshot()
    job = snap.jobs[pg.uid]
    job.pod_group.status.phase = "Running"
    job.pod_group.status.running = 2
    store.update_job_status(job)
    assert m.j_phase_code[row] == 3
    assert m.j_st_run[row] == 2

    # Condition write via record_job_condition refreshes the signature.
    cond = PodGroupCondition(
        type="Unschedulable", status="True", transition_id="t",
        reason="NotEnoughResources", message="0/2 ready",
    )
    store.record_job_condition(job, cond)
    assert m.j_cond_sig[row] == (
        hash(("NotEnoughResources", "0/2 ready")) & 0x7FFFFFFFFFFFFFFF
    )
