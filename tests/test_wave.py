"""Wave-batched solver: invariants and agreement with the sequential solver.

The wave solver (ops/wave.py) trades exact per-task ordering for batched
device work; these tests pin down what it must still guarantee:

- no node oversubscription (epsilon-aware),
- gang atomicity (committed jobs meet min_available; discarded jobs leave
  no allocations behind),
- full placement parity with the sequential solver on feasible workloads,
- determinism,
- per-feature paths (selectors, taints, queues/overuse gating, gangs too
  big to fit) behave like the sequential solver's.
"""

import numpy as np
import pytest

from volcano_tpu.api import Node, Pod, PodGroup, Queue
from volcano_tpu.cache import ClusterStore
from volcano_tpu.ops.allocate import solve
from volcano_tpu.ops.wave import solve_wave
from volcano_tpu.synth import solve_args_from_store, synthetic_cluster


def _placed(res):
    return int((np.asarray(res.assigned) >= 0).sum())


def _check_invariants(args, res):
    nodes, tasks, jobs = args[0], args[1], args[2]
    assigned = np.asarray(res.assigned)
    idle0 = np.asarray(nodes.idle)
    req = np.asarray(tasks.req)
    use = np.zeros_like(idle0)
    for i, n in enumerate(assigned):
        if n >= 0:
            use[n] += req[i]
    assert (use <= idle0 + 1e-3).all(), "node oversubscription"

    job = np.asarray(tasks.job)
    real = np.asarray(tasks.real)
    minav = np.asarray(jobs.min_available)
    rb = np.asarray(jobs.ready_base)
    counts = {}
    for i in range(len(assigned)):
        if real[i] and assigned[i] >= 0:
            counts[job[i]] = counts.get(job[i], 0) + 1
    for j, c in counts.items():
        assert rb[j] + c >= minav[j], (
            f"gang violated: job {j} committed {c} < min {minav[j]}"
        )
    never = np.asarray(res.never_ready)
    for i in range(len(assigned)):
        if real[i] and never[job[i]]:
            assert assigned[i] == -1, "discarded job left an allocation"


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_wave_invariants_randomized(seed):
    rng = np.random.RandomState(seed)
    store = synthetic_cluster(
        n_nodes=int(rng.randint(16, 64)),
        n_pods=int(rng.randint(64, 256)),
        gang_size=int(rng.randint(1, 6)),
        n_queues=int(rng.randint(1, 3)),
        seed=seed,
    )
    args, _ = solve_args_from_store(store)
    res = solve_wave(*args, wave=64)
    _check_invariants(args, res)


def test_wave_full_placement_matches_sequential():
    """On a feasible workload both solvers place every task."""
    store = synthetic_cluster(n_nodes=64, n_pods=512, gang_size=4,
                              n_queues=2)
    args, _ = solve_args_from_store(store)
    seq = solve(*args)
    wav = solve_wave(*args, wave=128)
    assert _placed(seq) == _placed(wav) == 512
    # Total consumed capacity agrees.
    assert np.allclose(
        np.asarray(seq.idle).sum(), np.asarray(wav.idle).sum(), rtol=1e-4
    )


def test_wave_deterministic():
    store = synthetic_cluster(n_nodes=32, n_pods=128, gang_size=4)
    args, _ = solve_args_from_store(store)
    a = np.asarray(solve_wave(*args, wave=64).assigned)
    b = np.asarray(solve_wave(*args, wave=64).assigned)
    assert np.array_equal(a, b)


from volcano_tpu.synth import GROUP_NAME_ANNOTATION


def _one_node_store(cpu="8", mem="16Gi"):
    store = ClusterStore()
    store.add_node(
        Node(name="n0", allocatable={"cpu": cpu, "memory": mem})
    )
    return store


def _add_gang(store, name, replicas, min_member, cpu="1", mem="1Gi",
              node_selector=None):
    pg = PodGroup(name=name, min_member=min_member, queue="default")
    store.add_pod_group(pg)
    for k in range(replicas):
        store.add_pod(Pod(
            name=f"{name}-{k}",
            annotations={GROUP_NAME_ANNOTATION: name},
            containers=[{"cpu": cpu, "memory": mem}],
            node_selector=node_selector or {},
        ))


def test_wave_gang_discard_when_gang_cannot_fit():
    """A gang larger than the cluster commits nothing (stmt.Discard)."""
    store = _one_node_store(cpu="4")
    _add_gang(store, "big", replicas=8, min_member=8)
    args, _ = solve_args_from_store(store)
    res = solve_wave(*args, wave=8)
    assert _placed(res) == 0
    assert bool(np.asarray(res.never_ready).any())
    # Capacity fully restored by the rollback.
    assert np.allclose(np.asarray(res.idle), np.asarray(args[0].idle))


def test_wave_partial_gang_commits_at_min_available():
    """min_available below replicas commits the partial gang (gang.go)."""
    store = _one_node_store(cpu="4")
    _add_gang(store, "elastic", replicas=8, min_member=2)
    args, _ = solve_args_from_store(store)
    res = solve_wave(*args, wave=8)
    assert _placed(res) == 4  # node fits 4 of 8; 4 >= min_available=2
    assert not bool(np.asarray(res.never_ready).any())


def test_wave_node_selector_respected():
    store = ClusterStore()
    store.add_node(
        Node(name="bad", allocatable={"cpu": "64", "memory": "64Gi"})
    )
    store.add_node(
        Node(name="good", allocatable={"cpu": "64", "memory": "64Gi"},
             labels={"zone": "a"})
    )
    _add_gang(store, "pinned", replicas=2, min_member=2,
              node_selector={"zone": "a"})
    args, maps = solve_args_from_store(store)
    res = solve_wave(*args, wave=8)
    assigned = np.asarray(res.assigned)
    good = maps.node_index["good"]
    real = np.asarray(args[1].real)
    assert all(assigned[i] == good for i in range(len(real)) if real[i])


def test_wave_matches_sequential_on_heterogeneous_mix():
    """Mixed profiles, queues, and gang sizes: same totals as sequential."""
    store = synthetic_cluster(n_nodes=48, n_pods=384, gang_size=3,
                              n_queues=3, seed=7)
    args, _ = solve_args_from_store(store)
    seq = solve(*args)
    wav = solve_wave(*args, wave=96)
    _check_invariants(args, wav)
    assert _placed(wav) == _placed(seq)


def test_sparse_cnt0_path_matches_dense(monkeypatch):
    """Forcing the sparse on-device cnt0 scatter (the hyperscale upload
    avoidance) must produce the same schedule as the dense upload,
    including resident counts and task-axis padding truncation."""
    import volcano_tpu.ops.wave as wave
    from volcano_tpu.api import Node, Pod, PodGroup, GROUP_NAME_ANNOTATION
    from volcano_tpu.api.spec import AffinityTerm
    from volcano_tpu.cache import ClusterStore
    from volcano_tpu.synth import solve_args_from_store

    def build():
        store = ClusterStore()
        for z in range(2):
            for i in range(3):
                store.add_node(Node(
                    name=f"z{z}-n{i}",
                    allocatable={"cpu": "8", "memory": "16Gi", "pods": 32},
                    labels={"zone": f"z{z}"},
                ))
        # Resident pod matching the term -> nonzero cnt0 entry.
        store.add_pod_group(PodGroup(name="res", min_member=1))
        res = Pod(name="res-0", labels={"app": "db"},
                  containers=[{"cpu": "1", "memory": "1Gi"}],
                  annotations={GROUP_NAME_ANNOTATION: "res"},
                  node_name="z1-n0", phase="Running")
        store.add_pod(res)
        term = AffinityTerm(match_labels={"app": "db"},
                            topology_key="zone")
        store.add_pod_group(PodGroup(name="g", min_member=3))
        for k in range(3):
            store.add_pod(Pod(
                name=f"g-{k}", labels={"app": "db"},
                containers=[{"cpu": "1", "memory": "1Gi"}],
                annotations={GROUP_NAME_ANNOTATION: "g"},
                affinity=[term],
            ))
        return store

    args, _ = solve_args_from_store(build())
    dense = np.asarray(wave.solve_wave(*args).assigned)
    monkeypatch.setattr(wave, "CNT0_SPARSE_MIN", 0)
    args2, _ = solve_args_from_store(build())
    sparse = np.asarray(wave.solve_wave(*args2).assigned)
    assert np.array_equal(dense, sparse)
    assert (sparse >= 0).sum() == 3


def test_sparse_profile_tables_match_dense(monkeypatch):
    """Forcing the sparse profile-term shipping path (PROF_SPARSE_MIN=0)
    must produce identical placements to the dense path — guards the
    flag bit-packing and the device-side scatter rebuild."""
    import volcano_tpu.ops.wave as wave
    from volcano_tpu.api import (
        GROUP_NAME_ANNOTATION,
        AffinityTerm,
        Node,
        Pod,
        PodGroup,
    )
    from volcano_tpu.cache import ClusterStore
    from volcano_tpu.synth import solve_args_from_store

    def build():
        store = ClusterStore()
        for z in ("z1", "z2"):
            for i in range(2):
                store.add_node(Node(
                    name=f"{z}-n{i}",
                    allocatable={"cpu": "8", "memory": "16Gi"},
                    labels={"zone": z},
                ))
        res = Pod(name="seed", labels={"app": "db"},
                  containers=[{"cpu": "1", "memory": "1Gi"}],
                  node_name="z1-n0", phase="Running")
        store.add_pod(res)
        aff_term = AffinityTerm(match_labels={"app": "db"},
                                topology_key="zone")
        anti_term = AffinityTerm(match_labels={"app": "lonely"},
                                 topology_key="kubernetes.io/hostname")
        store.add_pod_group(PodGroup(name="g", min_member=3))
        for k in range(3):
            store.add_pod(Pod(
                name=f"g-{k}", labels={"app": "db"},
                containers=[{"cpu": "1", "memory": "1Gi"}],
                annotations={GROUP_NAME_ANNOTATION: "g"},
                affinity=[aff_term],
            ))
        store.add_pod_group(PodGroup(name="solo", min_member=2))
        for k in range(2):
            store.add_pod(Pod(
                name=f"solo-{k}", labels={"app": "lonely"},
                containers=[{"cpu": "1", "memory": "1Gi"}],
                annotations={GROUP_NAME_ANNOTATION: "solo"},
                anti_affinity=[anti_term],
            ))
        return store

    args, _ = solve_args_from_store(build())
    dense = np.asarray(wave.solve_wave(*args).assigned)
    monkeypatch.setattr(wave, "PROF_SPARSE_MIN", 0)
    args2, _ = solve_args_from_store(build())
    sparse = np.asarray(wave.solve_wave(*args2).assigned)
    assert np.array_equal(dense, sparse)
    assert (sparse >= 0).sum() == 5  # the 3 aff + 2 anti pending pods
