"""Test configuration: force a virtual 8-device CPU platform for JAX.

Multi-chip sharding is validated on a virtual CPU mesh
(xla_force_host_platform_device_count), matching how the driver dry-runs the
multi-chip path; real-TPU benchmarking happens in bench.py.  The override
logic is shared with __graft_entry__.dryrun_multichip via
volcano_tpu.virtualcpu.
"""

import os

from volcano_tpu.virtualcpu import force_virtual_cpu_platform

force_virtual_cpu_platform(8)

# Fast-path exceptions must FAIL tests, not silently fall back to the
# object session (a fastpath bug could otherwise hide behind green
# tests that pass via the fallback).  Tests that exercise the fallback
# behavior itself override this with monkeypatch.setenv(..., "auto").
os.environ.setdefault("VOLCANO_TPU_FALLBACK", "never")

# The legacy preempt/reclaim suites (test_preempt_reclaim,
# test_evict_oracle, test_reclaim_multiqueue, ...) assert the reference
# host-walk semantics bind-for-bind against the object path; the
# device-native plan-prove-commit lane (ISSUE 11, volcano_tpu/whatif.py)
# is new semantics and its suites opt in explicitly with
# monkeypatch.setenv("VOLCANO_TPU_EVICT_DEVICE", "1").  Outside tests
# the device lane is the default.
os.environ.setdefault("VOLCANO_TPU_EVICT_DEVICE", "0")
