"""Test configuration: force a virtual 8-device CPU platform for JAX.

Multi-chip sharding is validated on a virtual CPU mesh
(xla_force_host_platform_device_count), matching how the driver dry-runs the
multi-chip path; real-TPU benchmarking happens in bench.py.

Note: the environment's TPU plugin pins jax_platforms at interpreter startup
(before conftest runs), so the env var alone is not enough — we override the
live jax config after import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
