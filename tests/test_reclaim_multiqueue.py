"""Cross-queue reclaim round-robin semantics (the multi-queue C drive).

Deterministic scenarios pinning what the randomized fuzz covers
statistically: queue ordering by live share under proportion, the
round-robin interleave across pending queues, overused verdicts frozen
at first evaluation, and fast-vs-object identity on a constructed
two-queue shape.
"""

import pytest

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    PodPhase,
    PriorityClass,
    Queue,
)
from volcano_tpu.cache import ClusterStore
from volcano_tpu.scheduler import Scheduler

EVICT_CONF = """
actions: "enqueue, allocate, preempt, reclaim, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


def two_queue_store(n_nodes=6, hi_a=3, hi_b=3):
    """Victim queue fully occupying ``n_nodes`` 16-cpu nodes; two
    pending premium queues (weights 6 and 3) each with single-pod
    8-cpu reclaimer jobs."""
    s = ClusterStore()
    s.add_priority_class(PriorityClass(name="low", value=100))
    s.add_priority_class(PriorityClass(name="high", value=10000))
    s.add_queue(Queue(name="victim", weight=1))
    s.add_queue(Queue(name="prem-a", weight=6))
    s.add_queue(Queue(name="prem-b", weight=3))
    for i in range(n_nodes):
        s.add_node(Node(name=f"n{i}",
                        allocatable={"cpu": "16", "memory": "64Gi",
                                     "pods": 64}))
        for k in range(2):
            pg = PodGroup(name=f"fill-{i}-{k}", min_member=1,
                          queue="victim")
            s.add_pod_group(pg)
            s.add_pod(Pod(
                name=f"fill-{i}-{k}-0",
                annotations={GROUP_NAME_ANNOTATION: pg.name},
                containers=[{"cpu": "8", "memory": "16Gi"}],
                phase=PodPhase.Running, node_name=f"n{i}",
                priority_class="low", priority=100,
            ))
    for q, count in (("prem-a", hi_a), ("prem-b", hi_b)):
        for j in range(count):
            pg = PodGroup(name=f"{q}-hi-{j}", min_member=1, queue=q)
            s.add_pod_group(pg)
            s.add_pod(Pod(
                name=f"{q}-hi-{j}-0",
                annotations={GROUP_NAME_ANNOTATION: pg.name},
                containers=[{"cpu": "8", "memory": "16Gi"}],
                priority_class="high", priority=10000,
            ))
    return s


def evicts(store):
    return set(getattr(store.evictor, "evicts", []))


def test_two_queue_fast_vs_object_identity(monkeypatch):
    stores = {}
    for mode, env in (("fast", "1"), ("object", "0")):
        monkeypatch.setenv("VOLCANO_TPU_FASTPATH", env)
        store = two_queue_store()
        Scheduler(store, conf_str=EVICT_CONF).run_once()
        stores[mode] = store
    assert evicts(stores["fast"]) == evicts(stores["object"])
    assert evicts(stores["fast"])  # something actually happened


def test_round_robin_serves_both_queues(monkeypatch):
    """With capacity for all reclaimers, both premium queues' jobs get
    victims — the round-robin never starves the lower-weight queue."""
    monkeypatch.setenv("VOLCANO_TPU_FASTPATH", "1")
    s = two_queue_store(n_nodes=6, hi_a=3, hi_b=3)
    Scheduler(s, conf_str=EVICT_CONF).run_once()
    # 6 reclaimers x 8 cpu over 6 nodes of 2x8 cpu victims: every
    # reclaimer can be covered by one eviction.
    assert len(evicts(s)) == 6


def test_mq_drive_engages_on_two_queues(monkeypatch):
    from volcano_tpu.native import reclaim_lib

    if reclaim_lib() is None:
        pytest.skip("native engine unavailable")
    monkeypatch.setenv("VOLCANO_TPU_FASTPATH", "1")
    import volcano_tpu.fastpath_evict as FE

    called = {"n": 0, "ok": 0}
    orig = FE.FastEvictor._native_reclaim_drive

    def spy(self, *a, **k):
        called["n"] += 1
        out = orig(self, *a, **k)
        called["ok"] += bool(out)
        return out

    FE.FastEvictor._native_reclaim_drive = spy
    try:
        s = two_queue_store()
        Scheduler(s, conf_str=EVICT_CONF).run_once()
    finally:
        FE.FastEvictor._native_reclaim_drive = orig
    assert called["n"] >= 1
    assert called["ok"] == called["n"], "MQ drive fell back to Python"


def test_unreclaimable_queue_protects_its_pods():
    """Victims in a reclaimable=False queue are never reclaimed even
    when two premium queues demand capacity."""
    s = ClusterStore()
    s.add_priority_class(PriorityClass(name="low", value=100))
    s.add_priority_class(PriorityClass(name="high", value=10000))
    s.add_queue(Queue(name="victim", weight=1, reclaimable=False))
    s.add_queue(Queue(name="prem-a", weight=6))
    s.add_queue(Queue(name="prem-b", weight=3))
    s.add_node(Node(name="n0", allocatable={"cpu": "16",
                                            "memory": "64Gi"}))
    for k in range(2):
        pg = PodGroup(name=f"fill-{k}", min_member=1, queue="victim")
        s.add_pod_group(pg)
        s.add_pod(Pod(
            name=f"fill-{k}-0",
            annotations={GROUP_NAME_ANNOTATION: pg.name},
            containers=[{"cpu": "8", "memory": "16Gi"}],
            phase=PodPhase.Running, node_name="n0",
            priority_class="low", priority=100,
        ))
    for q in ("prem-a", "prem-b"):
        pg = PodGroup(name=f"{q}-hi", min_member=1, queue=q)
        s.add_pod_group(pg)
        s.add_pod(Pod(
            name=f"{q}-hi-0",
            annotations={GROUP_NAME_ANNOTATION: pg.name},
            containers=[{"cpu": "8", "memory": "16Gi"}],
            priority_class="high", priority=10000,
        ))
    Scheduler(s, conf_str=EVICT_CONF).run_once()
    assert not evicts(s)


def test_three_pending_queues_parity(monkeypatch):
    """Three premium queues with distinct weights: the queue heap's
    live-share ordering must match the object path's PriorityQueue."""
    def build():
        s = two_queue_store(n_nodes=8, hi_a=2, hi_b=2)
        s.add_queue(Queue(name="prem-c", weight=2))
        for j in range(2):
            pg = PodGroup(name=f"prem-c-hi-{j}", min_member=1,
                          queue="prem-c")
            s.add_pod_group(pg)
            s.add_pod(Pod(
                name=f"prem-c-hi-{j}-0",
                annotations={GROUP_NAME_ANNOTATION: pg.name},
                containers=[{"cpu": "8", "memory": "16Gi"}],
                priority_class="high", priority=10000,
            ))
        return s

    res = {}
    for mode, env in (("fast", "1"), ("object", "0")):
        monkeypatch.setenv("VOLCANO_TPU_FASTPATH", env)
        store = build()
        Scheduler(store, conf_str=EVICT_CONF).run_once()
        res[mode] = evicts(store)
    assert res["fast"] == res["object"]
    assert res["fast"]


def test_yield_ratio_bail_keeps_parity(monkeypatch):
    """When most reclaimers carry host ports, the C drive yields
    repeatedly and bails to the Python loop mid-stream.  The bail must
    hand over coherent state (rebuilt job heaps, frozen overused
    verdicts) — fast and object paths stay identical."""
    def build():
        s = ClusterStore()
        s.add_priority_class(PriorityClass(name="low", value=100))
        s.add_priority_class(PriorityClass(name="high", value=10000))
        s.add_queue(Queue(name="victim", weight=1))
        s.add_queue(Queue(name="prem-a", weight=6))
        s.add_queue(Queue(name="prem-b", weight=3))
        for i in range(4):
            s.add_node(Node(name=f"n{i}",
                            allocatable={"cpu": "16", "memory": "64Gi",
                                         "pods": 64}))
            for k in range(2):
                pg = PodGroup(name=f"fill-{i}-{k}", min_member=1,
                              queue="victim")
                s.add_pod_group(pg)
                s.add_pod(Pod(
                    name=f"fill-{i}-{k}-0",
                    annotations={GROUP_NAME_ANNOTATION: pg.name},
                    containers=[{"cpu": "8", "memory": "16Gi"}],
                    phase=PodPhase.Running, node_name=f"n{i}",
                    priority_class="low", priority=100,
                ))
        # Most reclaimers carry host ports -> every turn yields -> the
        # yield-ratio bail fires after the first few.
        idx = 0
        for q, count in (("prem-a", 3), ("prem-b", 3)):
            for j in range(count):
                pg = PodGroup(name=f"{q}-hi-{j}", min_member=1, queue=q)
                s.add_pod_group(pg)
                ports = [9100 + idx] if idx % 4 != 3 else []
                idx += 1
                s.add_pod(Pod(
                    name=f"{q}-hi-{j}-0",
                    annotations={GROUP_NAME_ANNOTATION: pg.name},
                    containers=[{"cpu": "8", "memory": "16Gi"}],
                    host_ports=ports,
                    priority_class="high", priority=10000,
                ))
        return s

    import volcano_tpu.fastpath_evict as FE

    bails = {"n": 0}
    orig = FE.FastEvictor._native_reclaim_drive

    def spy(self, *a, **k):
        out = orig(self, *a, **k)
        if not out:
            bails["n"] += 1
        return out

    monkeypatch.setattr(FE.FastEvictor, "_native_reclaim_drive", spy)
    res = {}
    for mode, env in (("fast", "1"), ("object", "0")):
        monkeypatch.setenv("VOLCANO_TPU_FASTPATH", env)
        store = build()
        Scheduler(store, conf_str=EVICT_CONF).run_once()
        res[mode] = evicts(store)
    assert res["fast"] == res["object"], res["fast"] ^ res["object"]
    assert res["fast"]
    from volcano_tpu.native import reclaim_lib

    if reclaim_lib() is not None:
        # The scenario must actually exercise the mid-stream bail, or
        # this degrades to a redundant parity test.
        assert bails["n"] >= 1, "bail path never fired"
