"""Rebalance subsystem tests (ISSUE 5, docs/rebalance.md): planner
kernel <-> oracle parity, the plan-improves-or-noop invariant, per-group
disruption-budget ceilings (including the pipelined stale-void path),
the simulator's eviction grace window, and the fragmented-cluster e2e —
a 32-task gang unschedulable under allocate+backfill alone binds after
one rebalance cycle with zero lost pods."""

import numpy as np
import pytest

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    PriorityClass,
)
from volcano_tpu.cache import ClusterStore, FakeBinder
from volcano_tpu.framework import (
    REBALANCE_SCHEDULER_CONF,
    parse_scheduler_conf,
)
from volcano_tpu.metrics import metrics
from volcano_tpu.oracle import oracle_rebalance
from volcano_tpu.ops.rebalance import frag_scores, select_drain_set
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.sim import ClusterSimulator

ALLOC_CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


def make_pod(name, group, cpu="1", mem="1Gi", **kw):
    return Pod(
        name=name,
        namespace="default",
        annotations={GROUP_NAME_ANNOTATION: group},
        containers=[{"cpu": cpu, "memory": mem}],
        **kw,
    )


def make_node(name, cpu="4", mem="16Gi"):
    return Node(name=name,
                allocatable={"cpu": cpu, "memory": mem, "pods": 110})


def _rebalance_outcomes(store):
    """Flight-recorder rebalance outcomes, oldest first."""
    return [r.rebalance for r in store.flight.recent()
            if r.rebalance is not None]


def _plans_count(outcome):
    key = (("outcome", outcome),)
    return metrics.rebalance_plans.data.get(key, 0.0)


def _fragmented_cluster(workers, spill, budget=None, gang_priority=True):
    """``workers`` 4-cpu nodes each stranded by a 3-cpu filler plus
    ``spill`` empty 3-cpu nodes: no node fits a whole-node (4 cpu) gang
    task until fillers migrate to the spill nodes."""
    store = ClusterStore(binder=FakeBinder())
    if gang_priority:
        store.add_priority_class(PriorityClass(name="high", value=1000))
    for i in range(workers):
        store.add_node(make_node(f"w{i}", cpu="4"))
    for i in range(spill):
        store.add_node(make_node(f"s{i}", cpu="3"))
    for i in range(workers):
        store.add_pod_group(PodGroup(name=f"f{i}", min_member=1,
                                     max_unavailable=budget))
        store.add_pod(make_pod(f"fill{i}", f"f{i}", cpu="3"))
    return store


def _add_gang(store, size, cpu="4", priority_class="high"):
    store.add_pod_group(PodGroup(name="gang", min_member=size,
                                 priority_class=priority_class))
    for i in range(size):
        store.add_pod(make_pod(f"g{i}", "gang", cpu=cpu))


# --------------------------------------------------------------- parity


def test_oracle_parity_fixed_seeds():
    """frag/fit planes and the greedy drain selection agree exactly
    with the Go-shaped oracle on randomized fragmented snapshots."""
    import jax

    for seed in range(6):
        rng = np.random.RandomState(seed)
        N, R, U = 24, 3, 2
        alloc = rng.uniform(2.0, 8.0, size=(N, R)).astype(np.float32)
        idle = (alloc * rng.uniform(0.0, 1.0, size=(N, R))).astype(
            np.float32)
        ev = (idle * rng.uniform(0.0, 1.5, size=(N, R))).astype(
            np.float32)
        ready = rng.rand(N) > 0.1
        prof_req = rng.uniform(0.5, 6.0, size=(U, R)).astype(np.float32)
        # Some profiles request nothing on some slots.
        prof_req[rng.rand(U, R) < 0.3] = 0.0
        eps = np.full(R, 1e-3, np.float32)
        victims_by_node = [
            [n * 10 + k for k in range(int(rng.randint(0, 3)))]
            for n in range(N)
        ]
        victim_group = {
            r: f"g{r % 5}" for rows in victims_by_node for r in rows
        }
        budget_left = {f"g{i}": int(rng.randint(0, 4))
                       for i in range(5)}
        need = int(rng.randint(1, 6))
        cap = int(rng.randint(1, N))

        fs = frag_scores(idle, alloc, ready, ev, prof_req, eps)
        frag, fit_now, fit_freed = jax.device_get(
            (fs.frag, fs.fit_now, fs.fit_freed))
        nodes, blocked = select_drain_set(
            frag, fit_now, fit_freed, need, victims_by_node,
            victim_group, dict(budget_left), cap)

        ref = oracle_rebalance(idle, alloc, ready, ev, prof_req, eps,
                               need, victims_by_node, victim_group,
                               dict(budget_left), cap)
        np.testing.assert_allclose(frag, ref.frag, atol=1e-5,
                                   err_msg=f"seed {seed}")
        np.testing.assert_array_equal(fit_now, ref.fit_now)
        np.testing.assert_array_equal(fit_freed, ref.fit_freed)
        assert (list(nodes) == ref.drain_nodes.tolist()
                if ref.feasible else nodes == []), f"seed {seed}"
        assert blocked == ref.budget_blocked, f"seed {seed}"


# ------------------------------------------------- plan-improves-or-noop


def test_plan_improves_or_noop_fixed_seeds(monkeypatch):
    """On randomized fragmented clusters the lane either commits a plan
    that strictly improves binds — the gang fully binds and every
    evicted filler is re-bound (zero lost pods) — or commits nothing
    and mutates nothing."""
    committed_any = False
    for seed in range(3):
        rng = np.random.RandomState(100 + seed)
        workers = int(rng.randint(6, 12))
        spill = workers + int(rng.randint(0, 4))
        gang = max(2, workers // 2)
        monkeypatch.setenv("VOLCANO_TPU_REBALANCE_DRAIN_CAP", str(workers))
        store = _fragmented_cluster(workers, spill)
        sched = Scheduler(store, conf_str=REBALANCE_SCHEDULER_CONF)
        sim = ClusterSimulator(store, grace_steps=1)
        sched.run_once()
        sim.step()
        _add_gang(store, gang)
        n_logical = len(store.pods)  # fillers + gang, all must survive
        sched.run_once()
        ledger = store.migrations
        if ledger is None or ledger.committed_plans == 0:
            # Noop: nothing evicted, nothing mutated.
            assert not any(p.deleting for p in store.pods.values()), \
                f"seed {seed}: evictions without a committed plan"
            continue
        committed_any = True
        for _ in range(12):
            sim.step()
            sched.run_once()
            if (sum(1 for p in store.pods.values()
                    if p.name.startswith("g") and p.node_name) >= gang
                    and not ledger.active(store)):
                break
        bound_gang = sum(1 for p in store.pods.values()
                         if p.name.startswith("g") and p.node_name)
        assert bound_gang >= gang, f"seed {seed}: gang did not bind"
        # Zero lost pods: every logical pod (original or its restored
        # successor) is present and placed.
        assert len(store.pods) == n_logical, f"seed {seed}: pod lost"
        unplaced = [p.name for p in store.pods.values()
                    if p.node_name is None]
        assert not unplaced, f"seed {seed}: unplaced after converge"
        store.close()
    assert committed_any, "no seed exercised the commit path"


# ----------------------------------------------------------------- budgets


def test_budget_zero_blocks_plan(monkeypatch):
    """max_unavailable=0 on every filler group makes the drain set
    unassemblable: the plan is rejected for budget, nothing is
    evicted."""
    monkeypatch.setenv("VOLCANO_TPU_REBALANCE_DRAIN_CAP", "8")
    before = _plans_count("rejected-budget")
    store = _fragmented_cluster(4, 4, budget=0)
    sched = Scheduler(store, conf_str=REBALANCE_SCHEDULER_CONF)
    sim = ClusterSimulator(store)
    sched.run_once()
    sim.step()
    _add_gang(store, 2)
    sched.run_once()
    assert store.migrations is None or not store.migrations.entries
    assert not any(p.deleting for p in store.pods.values())
    outcomes = _rebalance_outcomes(store)
    assert outcomes and outcomes[-1]["outcome"] == "rejected-budget"
    assert _plans_count("rejected-budget") == before + 1
    store.close()


def test_budget_ceiling_caps_wave_size(monkeypatch):
    """One shared filler group with max_unavailable=2 and a gang that
    needs only 2 drained nodes: the committed wave takes exactly the
    victims the budget allows, the group's disrupted count never
    exceeds the ceiling at any point of the migration, and the gang
    binds."""
    monkeypatch.setenv("VOLCANO_TPU_REBALANCE_DRAIN_CAP", "8")
    store = ClusterStore(binder=FakeBinder())
    store.add_priority_class(PriorityClass(name="high", value=1000))
    for i in range(4):
        store.add_node(make_node(f"w{i}", cpu="4"))
    for i in range(4):
        store.add_node(make_node(f"s{i}", cpu="3"))
    store.add_pod_group(PodGroup(name="fillers", min_member=1,
                                 max_unavailable=2))
    for i in range(4):
        store.add_pod(make_pod(f"fill{i}", "fillers", cpu="3"))
    sched = Scheduler(store, conf_str=REBALANCE_SCHEDULER_CONF)
    sim = ClusterSimulator(store, grace_steps=1)
    sched.run_once()
    sim.step()
    _add_gang(store, 2)  # needs 2 of the 4 worker nodes drained
    max_seen = 0
    bound = 0
    for _ in range(16):
        sched.run_once()
        ledger = store.migrations
        if ledger is not None:
            max_seen = max(max_seen,
                           ledger.disrupted(store, "default/fillers"))
        sim.step()
        bound = sum(1 for p in store.pods.values()
                    if p.name.startswith("g") and p.node_name)
        if bound >= 2:
            break
    assert max_seen <= 2, f"budget exceeded: {max_seen} disrupted"
    assert max_seen > 0, "no migration happened"
    assert bound >= 2, "gang did not bind"
    ledger = store.migrations
    assert ledger is not None and ledger.committed_plans == 1
    outcomes = [o for o in _rebalance_outcomes(store)
                if o["outcome"] == "committed"]
    assert outcomes and outcomes[0]["victims"] == 2
    store.close()


def test_failed_evict_dispatch_cancels_migration(monkeypatch):
    """An evictor failure reverts the victim to Running AND cancels its
    ledger entry: the budget is not pinned, the lane is not wedged, and
    the pod's eventual ordinary deletion is not 'restored'."""
    monkeypatch.setenv("VOLCANO_TPU_REBALANCE_DRAIN_CAP", "8")

    class FlakyEvictor:
        def __init__(self):
            self.fail = True

        def evict(self, pod):
            if self.fail:
                raise RuntimeError("evictor down")

    evictor = FlakyEvictor()
    store = _fragmented_cluster(4, 4)
    store.evictor = evictor
    sched = Scheduler(store, conf_str=REBALANCE_SCHEDULER_CONF)
    sim = ClusterSimulator(store, grace_steps=1)
    sched.run_once()
    sim.step()
    _add_gang(store, 2)
    sched.run_once()  # plan commits; every evict dispatch fails
    ledger = store.migrations
    assert ledger is not None and ledger.committed_plans == 1
    # All entries cancelled: nothing terminating, budgets unpinned,
    # the lane free to re-plan.
    assert not ledger.entries
    assert not ledger.active(store)
    assert not any(p.deleting for p in store.pods.values())
    assert all(p.phase == "Running" for p in store.pods.values()
               if p.name.startswith("fill"))
    # Evictor recovers: a later wave completes end to end (the
    # rejection backoff applies only to planning failures, not evictor
    # failures — but drive enough cycles either way).
    evictor.fail = False
    from volcano_tpu.fastpath import FastCycle

    for _ in range(FastCycle.REBALANCE_REJECT_BACKOFF + 10):
        sim.step()
        sched.run_once()
        if sum(1 for p in store.pods.values()
               if p.name.startswith("g") and p.node_name) >= 2:
            break
    assert sum(1 for p in store.pods.values()
               if p.name.startswith("g") and p.node_name) >= 2
    # Zero lost pods through the failure + retry.
    fillers = [p for p in store.pods.values()
               if p.name.startswith("fill")]
    assert len(fillers) == 4 and all(p.node_name for p in fillers)
    store.close()


def test_deliberate_delete_is_not_resurrected(monkeypatch):
    """Deleting a victim's workload mid-termination wins over the
    migration: the pod is NOT restored, and the drained ledger does not
    wedge the lane."""
    monkeypatch.setenv("VOLCANO_TPU_REBALANCE_DRAIN_CAP", "8")
    store = _fragmented_cluster(4, 4)
    sched = Scheduler(store, conf_str=REBALANCE_SCHEDULER_CONF)
    sim = ClusterSimulator(store, grace_steps=3)
    sched.run_once()
    sim.step()
    _add_gang(store, 2)
    sched.run_once()  # plan commits; victims enter the grace window
    ledger = store.migrations
    assert ledger is not None and ledger.entries
    victims = [p for p in store.pods.values() if p.deleting]
    assert victims
    # The operator removes one victim's workload outright.
    gone = victims[0]
    group_uid = gone.annotations[GROUP_NAME_ANNOTATION]
    store.delete_pod_group(f"default/{group_uid}")
    store.delete_pod(gone)
    assert all("-mig" not in p.uid for p in store.pods.values()
               if p.name == gone.name), "deleted workload resurrected"
    assert gone.uid not in ledger.entries
    # The remaining victims migrate normally and the ledger drains —
    # the lane is not wedged by the removed workload.
    for _ in range(12):
        sim.step()
        sched.run_once()
        if not ledger.active(store):
            break
    assert not ledger.active(store)
    store.close()


def test_pipelined_stale_commit_voids_cleanly(monkeypatch):
    """Pipelined stores park the plan and commit next cycle; a store
    mutation during the overlap voids the whole plan (stale-voided) and
    nothing is evicted."""
    monkeypatch.setenv("VOLCANO_TPU_REBALANCE_DRAIN_CAP", "8")
    before = _plans_count("stale-voided")
    store = _fragmented_cluster(4, 4)
    store.pipeline = True
    sched = Scheduler(store, conf_str=REBALANCE_SCHEDULER_CONF)
    sim = ClusterSimulator(store, grace_steps=1)
    sched.run_once()  # dispatches the fillers' solve
    sched.run_once()  # commits the filler binds
    sim.step()        # fillers start Running
    _add_gang(store, 2)
    # Pipelined starvation streak: the plan forms on the second
    # starved pass and parks on the store.
    sched.run_once()
    sched.run_once()
    parked = store._inflight_plan
    assert parked is not None, "plan did not park"
    # Concurrent mutation during the overlap window.
    store.add_pod(make_pod("intruder", "f0", cpu="1"))
    sched.run_once()
    # The stale plan was voided; the lane may already have parked a
    # FRESH plan against the post-mutation state — never the old one.
    assert store._inflight_plan is not parked
    outcomes = [o for o in _rebalance_outcomes(store)
                if o["outcome"] == "stale-voided"]
    assert outcomes, "stale plan did not void"
    assert _plans_count("stale-voided") >= before + 1
    assert not any(p.deleting for p in store.pods.values()), \
        "a voided plan must evict nothing"
    store.close()


def test_pipelined_plan_commits_when_fresh(monkeypatch):
    """Without concurrent mutations the parked plan commits next cycle
    and the migration completes end to end."""
    monkeypatch.setenv("VOLCANO_TPU_REBALANCE_DRAIN_CAP", "8")
    store = _fragmented_cluster(4, 4)
    store.pipeline = True
    sched = Scheduler(store, conf_str=REBALANCE_SCHEDULER_CONF)
    sim = ClusterSimulator(store, grace_steps=1)
    sched.run_once()  # dispatches the fillers' solve
    sched.run_once()  # commits the filler binds
    sim.step()        # fillers start Running
    _add_gang(store, 2)
    for _ in range(16):
        sched.run_once()
        sim.step()
        if sum(1 for p in store.pods.values()
               if p.name.startswith("g") and p.node_name) >= 2:
            break
    assert sum(1 for p in store.pods.values()
               if p.name.startswith("g") and p.node_name) >= 2
    ledger = store.migrations
    assert ledger is not None and ledger.committed_plans >= 1
    store.close()


# ------------------------------------------------------------- sim grace


def test_sim_grace_period_holds_capacity():
    """A deleting pod passes through Terminating for grace_steps ticks;
    its capacity frees only when the delete lands."""
    store = ClusterStore(binder=FakeBinder())
    store.add_node(make_node("n0", cpu="4"))
    store.add_pod_group(PodGroup(name="pg", min_member=1))
    store.add_pod(make_pod("p0", "pg", cpu="4"))
    sched = Scheduler(store, conf_str=ALLOC_CONF)
    sim = ClusterSimulator(store, grace_steps=2)
    sched.run_once()
    sim.step()
    pod = next(p for p in store.pods.values() if p.name == "p0")
    assert pod.phase == "Running"
    pod.deleting = True
    r1 = sim.step()
    assert r1["terminating"] == 1 and r1["deleted"] == 0
    # Capacity still charged: a same-size pod cannot bind yet.
    store.add_pod_group(PodGroup(name="pg2", min_member=1))
    store.add_pod(make_pod("p1", "pg2", cpu="4"))
    sched.run_once()
    assert next(p for p in store.pods.values()
                if p.name == "p1").node_name is None
    r2 = sim.step()
    assert r2["terminating"] == 1 and r2["deleted"] == 0
    r3 = sim.step()
    assert r3["deleted"] == 1
    sched.run_once()
    assert next(p for p in store.pods.values()
                if p.name == "p1").node_name == "n0"
    store.close()


def test_sim_grace_zero_is_instant():
    store = ClusterStore(binder=FakeBinder())
    store.add_node(make_node("n0"))
    store.add_pod_group(PodGroup(name="pg", min_member=1))
    store.add_pod(make_pod("p0", "pg"))
    sim = ClusterSimulator(store)
    pod = next(iter(store.pods.values()))
    pod.deleting = True
    assert sim.step()["deleted"] == 1
    assert not store.pods
    store.close()


# ------------------------------------------------------------------- e2e


def test_fragmented_cluster_e2e_32_task_gang(monkeypatch):
    """Acceptance e2e: a 32-task whole-node gang is unschedulable under
    allocate+backfill alone, binds after ONE rebalance cycle (plus the
    eviction grace window), with zero lost pods and budgets never
    exceeded."""
    monkeypatch.setenv("VOLCANO_TPU_REBALANCE_DRAIN_CAP", "32")
    workers, spill, gang = 32, 32, 32
    store = _fragmented_cluster(workers, spill)
    sched_alloc = Scheduler(store, conf_str=ALLOC_CONF)
    sim = ClusterSimulator(store, grace_steps=2)
    sched_alloc.run_once()
    sim.step()  # fillers start Running
    _add_gang(store, gang)
    n_logical = len(store.pods)

    # Unschedulable under allocate+backfill alone.
    sched_alloc.run_once()
    assert not any(p.node_name for p in store.pods.values()
                   if p.name.startswith("g"))
    conds = store.pod_groups["default/gang"].status.conditions
    assert any(c.type == "Unschedulable" for c in conds)

    # ONE rebalance cycle plans and commits the full migration wave.
    sched = Scheduler(store, conf_str=REBALANCE_SCHEDULER_CONF)
    sched.run_once()
    ledger = store.migrations
    assert ledger is not None and ledger.committed_plans == 1
    assert len(ledger.entries) == workers  # every filler migrating
    outcomes = _rebalance_outcomes(store)
    assert outcomes[-1]["outcome"] == "committed"
    assert outcomes[-1]["victims"] == workers
    evicted = [p.name for p in store.pods.values() if p.deleting]
    assert len(evicted) == workers

    # Budgets (max_unavailable default 1 per single-member group): no
    # group ever has more than one member disrupted.
    for i in range(workers):
        assert ledger.disrupted(store, f"default/f{i}") <= 1

    # Drive the migration through the grace window to convergence.
    converged = False
    for _ in range(12):
        sim.step()
        sched.run_once()
        gang_bound = sum(1 for p in store.pods.values()
                         if p.name.startswith("g") and p.node_name)
        if gang_bound >= gang and not ledger.active(store):
            converged = True
            break
    assert converged, "migration did not converge"

    # The gang landed on the drained worker nodes; every filler
    # (original or restored) is bound; zero lost pods.
    assert len(store.pods) == n_logical
    gang_nodes = sorted(p.node_name for p in store.pods.values()
                        if p.name.startswith("g"))
    assert all(n and n.startswith("w") for n in gang_nodes)
    fillers = [p for p in store.pods.values()
               if p.name.startswith("fill")]
    assert len(fillers) == workers
    assert all(p.node_name for p in fillers)
    assert ledger.committed_plans == 1, "one wave sufficed"
    # The restored fillers all landed on spill nodes.
    restored = [p for p in fillers if "-mig" in p.uid]
    assert len(restored) == workers
    assert all(p.node_name.startswith("s") for p in restored)
    store.close()


def test_rebalance_disabled_by_env(monkeypatch):
    """VOLCANO_TPU_REBALANCE=0 turns the configured action into a
    no-op without a config change."""
    monkeypatch.setenv("VOLCANO_TPU_REBALANCE", "0")
    store = _fragmented_cluster(4, 4)
    sched = Scheduler(store, conf_str=REBALANCE_SCHEDULER_CONF)
    sim = ClusterSimulator(store)
    sched.run_once()
    sim.step()
    _add_gang(store, 2)
    sched.run_once()
    assert store.migrations is None
    assert not any(p.deleting for p in store.pods.values())
    store.close()


def test_object_path_rebalance_action_is_noop(monkeypatch):
    """A configuration that forces the object session still accepts the
    action name (registered no-op) instead of warning/failing."""
    monkeypatch.setenv("VOLCANO_TPU_FASTPATH", "0")
    monkeypatch.setenv("VOLCANO_TPU_FALLBACK", "always")
    store = _fragmented_cluster(2, 2)
    sched = Scheduler(store, conf_str=REBALANCE_SCHEDULER_CONF)
    sched.run_once()  # must not raise
    assert store.migrations is None
    store.close()
