"""Fast-path parity: the vectorized cycle must produce the same binds and
pod-group phases as the object-session path on identical stores."""

import os

import pytest

from volcano_tpu.framework import parse_scheduler_conf
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.synth import synthetic_cluster

CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


def _run(store, fast: bool):
    os.environ["VOLCANO_TPU_FASTPATH"] = "1" if fast else "0"
    try:
        Scheduler(store, conf_str=CONF).run_once()
    finally:
        os.environ.pop("VOLCANO_TPU_FASTPATH", None)
    return store


def _state(store):
    binds = dict(store.binder.binds)
    phases = {
        uid: pg.status.phase for uid, pg in sorted(store.pod_groups.items())
    }
    return binds, phases


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize(
    "kwargs",
    [
        dict(n_nodes=8, n_pods=40, gang_size=4),
        dict(n_nodes=12, n_pods=60, gang_size=3, n_queues=3,
             queue_weights=(1, 2, 4)),
        dict(n_nodes=6, n_pods=30, gang_size=5, zones=2,
             affinity_fraction=0.2, anti_affinity_fraction=0.1,
             spread_fraction=0.2),
    ],
)
def test_fast_matches_object_path(seed, kwargs):
    a = _run(synthetic_cluster(seed=seed, **kwargs), fast=False)
    b = _run(synthetic_cluster(seed=seed, **kwargs), fast=True)
    binds_a, phases_a = _state(a)
    binds_b, phases_b = _state(b)
    assert binds_b == binds_a
    assert phases_b == phases_a


def test_fast_path_used(monkeypatch):
    """The eligible default conf actually takes the fast path."""
    import volcano_tpu.fastpath as fp

    called = {}
    orig = fp.FastCycle.run

    def spy(self):
        called["yes"] = True
        return orig(self)

    monkeypatch.setattr(fp.FastCycle, "run", spy)
    store = synthetic_cluster(n_nodes=4, n_pods=8, gang_size=2)
    Scheduler(store, conf_str=CONF).run_once()
    assert called.get("yes")


def test_object_model_rebuild_after_fast_cycle():
    store = synthetic_cluster(n_nodes=4, n_pods=12, gang_size=3)
    Scheduler(store, conf_str=CONF).run_once()
    # Accessing the object model after a fast commit rebuilds it from pods.
    total_bound = sum(
        1 for p in store.pods.values() if p.node_name
    )
    assert total_bound == len(store.binder.binds)
    node_tasks = sum(len(n.tasks) for n in store.nodes.values())
    assert node_tasks == total_bound
    # Node accounting balances.
    for node in store.nodes.values():
        assert node.idle.milli_cpu >= -1e-6


def test_chunked_solve_matches_unchunked(monkeypatch):
    """Forcing a tiny affinity budget splits the solve into job-aligned
    chunks with commits in between; the set of binds must match the
    single-call solve (later chunks seeing earlier placements is the
    sequential reference's own semantics)."""
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.synth import synthetic_cluster

    kw = dict(n_nodes=16, n_pods=96, gang_size=4, zones=4,
              affinity_fraction=0.2, anti_affinity_fraction=0.1,
              spread_fraction=0.2, seed=3)
    a = synthetic_cluster(**kw)
    Scheduler(a).run_once()
    monkeypatch.setenv("VOLCANO_TPU_AFF_BUDGET_MB", "0.0001")
    b = synthetic_cluster(**kw)
    Scheduler(b).run_once()
    assert len(b.binder.binds) == len(a.binder.binds)
    assert set(b.binder.binds) == set(a.binder.binds)


def test_bind_failure_resyncs_tasks_to_pending():
    """A binder reporting partial failure (BindFailure) reverts exactly
    the failed tasks to Pending — the errTasks resync semantics
    (cache.go:627-649) — and the next cycle retries them."""
    from volcano_tpu.cache.interface import BindFailure
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.synth import synthetic_cluster

    store = synthetic_cluster(n_nodes=8, n_pods=24, gang_size=1)
    orig_bind_keys = store.binder.bind_keys
    state = {"fail_once": True}

    def flaky_bind_keys(keys, hosts):
        if state["fail_once"]:
            state["fail_once"] = False
            ok = [(k, h) for k, h in zip(keys, hosts)][: len(keys) // 2]
            orig_bind_keys([k for k, _ in ok], [h for _, h in ok])
            raise BindFailure([k for k in keys[len(keys) // 2:]])
        orig_bind_keys(keys, hosts)

    store.binder.bind_keys = flaky_bind_keys
    sched = Scheduler(store)
    sched.run_once()
    bound_1 = len(store.binder.binds)
    assert bound_1 == 12
    # Failed tasks are Pending again, not phantom-bound.
    pending = [p for p in store.pods.values() if p.node_name is None]
    assert len(pending) == 12
    # Next cycle rebinds them.
    sched.run_once()
    assert len(store.binder.binds) == 24
    assert all(p.node_name for p in store.pods.values())


def test_enqueue_phase_transition_persisted_despite_writeback_skip():
    """The close write-back skips unchanged PodGroups, but enqueue's
    in-place Pending -> Inqueue mutation must still persist + notify
    (the status updater is the API-server boundary)."""
    from volcano_tpu.api import Node, PodGroup
    from volcano_tpu.cache import ClusterStore
    from volcano_tpu.scheduler import Scheduler

    store = ClusterStore()
    store.add_node(Node(name="n0", allocatable={"cpu": "4",
                                                "memory": "8Gi"}))
    store.add_pod_group(PodGroup(name="g", min_member=1,
                                 min_resources={"cpu": "1"}))
    phases = []
    orig = store.status_updater.update_pod_group
    store.status_updater.update_pod_group = (
        lambda pg: (phases.append(pg.status.phase), orig(pg))[1]
    )
    Scheduler(store).run_once()
    assert "Inqueue" in phases, f"Inqueue not persisted: {phases}"


def test_enqueue_transition_survives_failed_cycle(monkeypatch):
    """A cycle that fails AFTER enqueue's in-place Inqueue mutation must
    not strand the transition: the next successful cycle still persists
    it (the dirty set lives on the store, cleared only after a
    successful write-back)."""
    import volcano_tpu.fastpath as fp
    from volcano_tpu.api import Node, PodGroup
    from volcano_tpu.cache import ClusterStore
    from volcano_tpu.scheduler import Scheduler

    store = ClusterStore()
    store.add_node(Node(name="n0", allocatable={"cpu": "4",
                                                "memory": "8Gi"}))
    store.add_pod_group(PodGroup(name="g", min_member=1,
                                 min_resources={"cpu": "1"}))
    phases = []
    orig_update = store.status_updater.update_pod_group
    store.status_updater.update_pod_group = (
        lambda pg: (phases.append(pg.status.phase), orig_update(pg))[1]
    )
    orig_alloc = fp.FastCycle._allocate
    calls = {"n": 0}

    def failing_alloc(self):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("device failure after enqueue")
        return orig_alloc(self)

    monkeypatch.setattr(fp.FastCycle, "_allocate", failing_alloc)
    # This test exercises the production fallback path by design.
    monkeypatch.setenv("VOLCANO_TPU_FALLBACK", "auto")
    sched = Scheduler(store)
    sched.run_once()  # fast cycle fails post-enqueue; object path covers
    phases.clear()
    # Force the interesting path: a later FAST cycle must persist the
    # still-pending transition even though the phase compares equal.
    store._phase_dirty_uids.add("default/g")
    sched.run_once()
    assert "Inqueue" in phases or "Running" in phases, (
        f"stranded transition never persisted: {phases}"
    )
    assert not store._phase_dirty_uids


def test_enqueue_accept_all_eps_boundary_falls_back_to_walk():
    """When pending groups' MinResources total exactly consumes the
    overcommitted idle budget, the sequential walk (enqueue.go:98-101)
    accepts groups until idle goes empty and rejects everything after —
    including MinResources-nil groups that charge nothing.  The
    accept-all shortcut must not diverge at this eps boundary (it
    requires a non-empty residual before accepting, else falls through
    to the walk)."""
    from volcano_tpu.api import Node, PodGroup
    from volcano_tpu.cache import ClusterStore
    from volcano_tpu.scheduler import Scheduler

    store = ClusterStore()
    store.add_node(Node(name="n0", allocatable={"cpu": "10",
                                                "memory": "10Gi"}))
    # "a" consumes the whole 1.2x-overcommitted idle (12 cpu / 12Gi).
    store.add_pod_group(PodGroup(name="a", min_member=1,
                                 min_resources={"cpu": "12",
                                                "memory": "12Gi"}))
    store.add_pod_group(PodGroup(name="b", min_member=1))
    Scheduler(store).run_once()
    phases = {pg.name: pg.status.phase
              for pg in store.pod_groups.values()}
    assert phases["a"] == "Inqueue"
    # The walk broke once idle went empty, so "b" never got examined.
    assert phases["b"] == "Pending", phases


def test_fastpath_volume_gate_and_revert():
    """Fast-path commit runs claims through the volume binder before the
    pod bind dispatches: an existing claim binds with the pod; a missing
    claim reverts exactly that pod to Pending (statement.go allocate->
    AllocateVolumes, commit->BindVolumes semantics)."""
    from volcano_tpu.api import GROUP_NAME_ANNOTATION, Node, Pod, PodGroup
    from volcano_tpu.cache import ClusterStore
    from volcano_tpu.scheduler import Scheduler

    store = ClusterStore()
    store.add_node(Node(name="n0", allocatable={"cpu": "8",
                                                "memory": "16Gi"}))
    store.put_pvc("default", "good-claim", {"storage": "1Gi"})
    store.add_pod_group(PodGroup(name="g", min_member=1))
    store.add_pod_group(PodGroup(name="h", min_member=1))
    store.add_pod(Pod(
        name="with-claim",
        containers=[{"cpu": "1", "memory": "1Gi"}],
        annotations={GROUP_NAME_ANNOTATION: "g"},
        volumes=[("good-claim", "/data")],
    ))
    store.add_pod(Pod(
        name="no-claim",
        containers=[{"cpu": "1", "memory": "1Gi"}],
        annotations={GROUP_NAME_ANNOTATION: "h"},
        volumes=[("vanished", "/data")],
    ))
    Scheduler(store).run_once()

    by_name = {p.name: p for p in store.pods.values()}
    assert by_name["with-claim"].node_name == "n0"
    assert store.pvcs["default/good-claim"]["phase"] == "Bound"
    assert store.pvcs["default/good-claim"]["node"] == "n0"
    # The claimless pod reverted: not bound, not dispatched to the binder.
    assert by_name["no-claim"].node_name is None
    assert "default/no-claim" not in store.binder.binds
    evs = store.events_for("Pod/default/no-claim")
    assert any(e["reason"] == "FailedScheduling"
               and "vanished" in e["message"] for e in evs)
    # Node accounting reverted with it: only one pod's worth used.
    ni = store.nodes["n0"]
    assert int(ni.used.milli_cpu) == 1000


def test_cycle_lane_breakdown_published():
    """Each fast cycle publishes its per-lane wall-clock split
    (store.last_cycle_lanes) — the bench/operator visibility surface."""
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.synth import synthetic_cluster

    store = synthetic_cluster(n_nodes=8, n_pods=24, gang_size=2)
    Scheduler(store).run_once()
    lanes = store.last_cycle_lanes
    for key in ("derive", "order", "encode", "device", "commit",
                "close", "enqueue"):
        assert key in lanes and lanes[key] >= 0.0, (key, lanes)
    # Sanity: lanes are a breakdown, not garbage — each under a minute.
    assert all(v < 60 for v in lanes.values())
