"""The 100k-node x 1M-pod scale tier (ISSUE 12).

Tier-1 runs a scaled-down proxy of the tier generator plus the
devsnap chunk-budget machinery at toy shapes; the full shape is
``@pytest.mark.slow`` (CI-class tier-1 hosts budget ~15 minutes for
the whole suite — the 1M-pod build alone is minutes).
"""

import numpy as np
import pytest

from volcano_tpu.ops.devsnap import DeviceSnapshot
from volcano_tpu.synth import tier_cluster


class _FakeMirror:
    """Just enough mirror for DeviceSnapshot.node_planes."""

    def __init__(self):
        self.rows = None

    def node_delta_rows(self, epoch):
        return self.rows

    def reset_node_delta(self):
        self.rows = None


def test_devsnap_chunked_delta_scatter(monkeypatch):
    """A delta past the staging budget scatters in bounded chunks and
    lands bit-identical to the unchunked result; the resident-bytes
    model matches the committed planes."""
    monkeypatch.setenv("VOLCANO_TPU_DEVSNAP_BUDGET_MB", "0.000001")
    snap = DeviceSnapshot()
    N, R = 64, 1024  # 4 KB f32 rows against the 4 KB budget floor
    base = np.arange(N * R, dtype=np.float32).reshape(N, R)
    m = _FakeMirror()
    build = {"p": lambda rows, b=base: b if rows is None else b[rows]}
    planes = snap.node_planes(m, (0, N), build)
    assert snap.full_uploads == 1 and snap.delta_chunks == 0
    base[5:13] += 1000.0
    m.rows = np.arange(5, 13)
    planes = snap.node_planes(m, (1, N), build)
    assert snap.delta_uploads == 1
    assert snap.delta_chunks >= 7  # 8 rows / 1-row chunks
    assert np.array_equal(np.asarray(planes["p"]), base)
    assert snap.resident_bytes() == base.nbytes


def test_devsnap_default_budget_single_scatter():
    """Under the default budget a small delta stays one scatter (the
    chunking must not tax the steady-state path)."""
    snap = DeviceSnapshot()
    N, R = 64, 8
    base = np.zeros((N, R), np.float32)
    m = _FakeMirror()
    build = {"p": lambda rows, b=base: b if rows is None else b[rows]}
    snap.node_planes(m, (0, N), build)
    base[4] = 9.0
    m.rows = np.asarray([4])
    snap.node_planes(m, (1, N), build)
    assert snap.delta_uploads == 1 and snap.delta_chunks == 0


def test_tier_generator_memory_frugal_sharing():
    """The chunked pod-table fill shares sub-objects: one annotations
    dict per gang, one containers list per pod shape — the per-pod
    Python-object overhead the 1M build cannot afford."""
    store = tier_cluster(n_nodes=32, n_pods=256, gang_size=8, zones=4,
                         chunk_pods=64)
    pods = sorted(store.pods.values(), key=lambda p: p.name)
    assert len(pods) == 256
    by_gang = {}
    for p in pods:
        by_gang.setdefault(p.job_id(), []).append(p)
    assert len(by_gang) == 32
    for members in by_gang.values():
        first = members[0]
        for p in members[1:]:
            assert p.annotations is first.annotations
            assert p.containers is first.containers
    # Containers lists dedupe ACROSS gangs too (one per shape).
    distinct = {id(p.containers) for p in pods}
    assert len(distinct) <= 9  # |cpu choices| x |mem choices|
    store.close()


def test_tier_proxy_cycle_binds():
    """Scaled-down tier proxy: one full cycle completes, gangs bind,
    and the devsnap footprint stays within the modeled envelope."""
    from volcano_tpu.scheduler import Scheduler

    store = tier_cluster(n_nodes=256, n_pods=2048, gang_size=8,
                         zones=8, chunk_pods=1024)
    Scheduler(store).run_once()
    bound = sum(1 for p in store.pods.values() if p.node_name)
    assert bound == 2048  # 256 x 64cpu swallows 2048 small pods
    snap = getattr(store, "device_snapshot", None)
    if snap is not None:
        # Node planes at the proxy shape: well under a few MB; the
        # model (sum of committed plane nbytes) must agree with what
        # the cycle actually left resident.
        assert 0 < snap.resident_bytes() < 32 * 1024 * 1024
    store.close()


@pytest.mark.slow
def test_tier_100k_x_1m_full_cycle_under_budget():
    """The full 100k x 1M shape: chunked build completes on a CI-class
    host, one cycle binds a nonzero wave, and peak RSS stays under the
    modeled envelope (the generator's shared sub-objects + the chunked
    encode/scatter paths are what make this fit)."""
    import resource

    from volcano_tpu.scheduler import Scheduler

    store = tier_cluster()  # 100_000 x 1_000_000
    assert len(store.pods) == 1_000_000
    Scheduler(store).run_once()
    bound = sum(1 for p in store.pods.values() if p.node_name)
    assert bound > 0
    peak_gb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    assert peak_gb < 64, f"peak RSS {peak_gb:.1f} GB exceeds the budget"
    store.close()
