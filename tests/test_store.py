"""Cluster store + snapshot tests.

Mirrors the test pattern of the reference's cache tests
(``pkg/scheduler/cache/event_handlers_test.go`` and the builder helpers in
``pkg/scheduler/util/test_utils.go:33-92``): build pods/nodes/podgroups/queues
through the event API and assert the derived accounting.
"""

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    PodPhase,
    Queue,
    TaskStatus,
)
from volcano_tpu.cache import ClusterStore, FakeBinder


def build_pod(name, ns="default", group="pg1", cpu="1", mem="1Gi", phase=PodPhase.Pending, node=None):
    return Pod(
        name=name,
        namespace=ns,
        annotations={GROUP_NAME_ANNOTATION: group} if group else {},
        containers=[{"cpu": cpu, "memory": mem}],
        phase=phase,
        node_name=node,
    )


def build_node(name, cpu="4", mem="8Gi", pods=110):
    return Node(name=name, allocatable={"cpu": cpu, "memory": mem, "pods": pods})


def test_default_queue_created():
    store = ClusterStore()
    assert "default" in store.queues
    assert store.queues["default"].weight == 1


def test_add_pod_builds_job_and_node_accounting():
    store = ClusterStore(binder=FakeBinder())
    store.add_node(build_node("n1"))
    store.add_pod_group(PodGroup(name="pg1", min_member=2))
    store.add_pod(build_pod("p1"))
    store.add_pod(build_pod("p2", phase=PodPhase.Running, node="n1"))

    job = store.jobs["default/pg1"]
    assert len(job.tasks) == 2
    assert job.min_available == 2
    # Running pod holds node resources.
    n1 = store.nodes["n1"]
    assert n1.used.milli_cpu == 1000
    assert n1.idle.milli_cpu == 3000


def test_snapshot_is_deep_copy():
    store = ClusterStore()
    store.add_node(build_node("n1"))
    store.add_pod_group(PodGroup(name="pg1", min_member=1))
    store.add_pod(build_pod("p1"))

    snap = store.snapshot()
    assert "default/pg1" in snap.jobs
    # Mutating the snapshot must not touch the store.
    snap.nodes["n1"].idle.milli_cpu = 0
    assert store.nodes["n1"].idle.milli_cpu == 4000
    snap_job = snap.jobs["default/pg1"]
    task = next(iter(snap_job.tasks.values()))
    snap_job.update_task_status(task, TaskStatus.Allocated)
    stored_task = next(iter(store.jobs["default/pg1"].tasks.values()))
    assert stored_task.status == TaskStatus.Pending


def test_job_without_podgroup_not_in_snapshot():
    store = ClusterStore()
    store.add_pod(build_pod("p1", group="orphan-pg"))
    snap = store.snapshot()
    assert "default/orphan-pg" not in snap.jobs


def test_bind_updates_store_and_binder():
    binder = FakeBinder()
    store = ClusterStore(binder=binder)
    store.add_node(build_node("n1"))
    store.add_pod_group(PodGroup(name="pg1", min_member=1))
    pod = build_pod("p1")
    store.add_pod(pod)

    job = store.jobs["default/pg1"]
    task = next(iter(job.tasks.values()))
    store.bind(task, "n1")

    assert binder.binds == {"default/p1": "n1"}
    # Pod now bound: node accounting reflects it.
    assert store.nodes["n1"].used.milli_cpu == 1000
    # Task status derives from pod state (Pending + node -> Bound).
    assert store.jobs["default/pg1"].tasks[task.uid].status == TaskStatus.Bound


def test_evict_marks_releasing():
    store = ClusterStore()
    store.add_node(build_node("n1"))
    store.add_pod_group(PodGroup(name="pg1", min_member=1))
    pod = build_pod("p1", phase=PodPhase.Running, node="n1")
    store.add_pod(pod)

    task = next(iter(store.jobs["default/pg1"].tasks.values()))
    store.evict(task, "preempt")
    n1 = store.nodes["n1"]
    assert n1.releasing.milli_cpu == 1000
    assert n1.used.milli_cpu == 1000


def test_node_future_idle():
    store = ClusterStore()
    store.add_node(build_node("n1"))
    store.add_pod_group(PodGroup(name="pg1", min_member=1))
    store.add_pod(build_pod("p1", phase=PodPhase.Running, node="n1"))
    task = next(iter(store.jobs["default/pg1"].tasks.values()))
    store.evict(task, "test")
    n1 = store.nodes["n1"]
    # future idle = idle + releasing - pipelined
    assert n1.future_idle().milli_cpu == 4000


def test_delete_pod_removes_accounting():
    store = ClusterStore()
    store.add_node(build_node("n1"))
    store.add_pod_group(PodGroup(name="pg1", min_member=1))
    pod = build_pod("p1", phase=PodPhase.Running, node="n1")
    store.add_pod(pod)
    store.delete_pod(pod)
    assert store.nodes["n1"].used.milli_cpu == 0
    assert len(store.jobs["default/pg1"].tasks) == 0


def test_terminated_pods_release_node_resources():
    # Succeeded/Failed pods must not consume node idle
    # (reference isTerminated filter in node accounting).
    store = ClusterStore()
    store.add_node(build_node("n1"))
    store.add_pod_group(PodGroup(name="pg1", min_member=1))
    pod = build_pod("p1", phase=PodPhase.Running, node="n1")
    store.add_pod(pod)
    assert store.nodes["n1"].idle.milli_cpu == 3000
    done = build_pod("p1", phase=PodPhase.Succeeded, node="n1")
    done.uid = pod.uid
    store.update_pod(done)
    assert store.nodes["n1"].idle.milli_cpu == 4000
    # Job still counts it for readiness.
    assert store.jobs["default/pg1"].ready_task_num() == 1


def test_ungrouped_bound_pod_occupies_node():
    # A pod with no group annotation but bound to a node must still be
    # visible in node accounting (cache.go tracks any pod with NodeName).
    store = ClusterStore()
    store.add_node(build_node("n1"))
    store.add_pod(build_pod("sys-daemon", group=None, cpu="2",
                            phase=PodPhase.Running, node="n1"))
    assert store.nodes["n1"].idle.milli_cpu == 2000
    snap = store.snapshot()
    assert snap.nodes["n1"].idle.milli_cpu == 2000
