"""Incremental host lanes (ISSUE 8): dirty-set derive parity, order/
encode cache parity, fallback behavior, and the dirty-set <-> staleness
guard agreement contract.

The acceptance bar is BIT-FOR-BIT: with ``VOLCANO_TPU_INCREMENTAL=1``,
every derive aggregate, the job ordering, and the solver inputs must
equal the full-rebuild path across randomized churn — and binds must be
identical end-to-end.
"""

import logging
import random

import numpy as np
import pytest

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    Queue,
    TaskStatus,
)
from volcano_tpu.fastpath import FastCycle
from volcano_tpu.framework import parse_scheduler_conf
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.synth import synthetic_cluster

CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


def _reset_uid_counters():
    """Pod uids / creation timestamps draw from process-global counters;
    twin runs must see identical universes to be comparable."""
    import itertools

    import volcano_tpu.api.spec as spec

    spec._uid_counter = itertools.count(1)
    spec._ts_counter = itertools.count(1)


def _inqueue_all(store):
    """Move every PodGroup to Inqueue so a derive-only probe sees
    schedulable jobs without running an enqueue action first."""
    for pg in list(store.pod_groups.values()):
        pg.status.phase = "Inqueue"
        store.update_pod_group(pg)


def _probe(store):
    """A derive-only FastCycle over the store (no solve, no actions)."""
    cyc = FastCycle(store, parse_scheduler_conf(CONF))
    with store._lock:
        cyc.derive()
        cyc._proportion()
    return cyc


def _assert_aggr_parity(store):
    """Every derive aggregate must equal a from-scratch build."""
    from volcano_tpu.fastpath_incr import _build_aggregates

    cyc = _probe(store)
    m = store.mirror
    with store._lock:
        (resident, used, rel, ntasks, counts, empty, alloc,
         pending) = _build_aggregates(m, cyc.Pn, cyc.Nn, cyc.R,
                                      cyc.n_alive)
    assert np.array_equal(cyc.resident, resident)
    # The PERSISTENT planes are the bit-for-bit contract (float64);
    # the cycle's copies are their f32 casts.
    assert np.array_equal(cyc.aggr.n_used, used)
    assert np.array_equal(cyc.aggr.n_releasing, rel)
    assert np.array_equal(cyc.n_used, used.astype(np.float32))
    assert np.array_equal(cyc.n_releasing, rel.astype(np.float32))
    assert np.array_equal(cyc.n_ntasks, ntasks.astype(np.int32))
    assert np.array_equal(cyc.aggr.js_counts, counts)
    assert np.array_equal(cyc.j_cnt_empty_pending,
                          empty.astype(np.int32))
    assert np.array_equal(cyc.aggr.j_alloc_res, alloc)
    assert np.array_equal(cyc.aggr.j_pending_res, pending)
    assert np.array_equal(cyc.j_alloc_res, alloc.astype(np.float32))
    assert np.array_equal(cyc.j_pending_res,
                          pending.astype(np.float32))
    return cyc


def _assert_rank_parity(store):
    """The merge-cached job rank must equal a fresh full lexsort."""
    cyc = _probe(store)
    with store._lock:
        drf = cyc._drf_shares()
        cached_rank = cyc._job_keys(cyc.session_jobs, drf)
        # Fresh, cache-free rank over the SAME key columns.
        store._job_rank_cache = None
        fresh_rank = cyc._job_keys(cyc.session_jobs, drf)
    assert np.array_equal(cached_rank, fresh_rank)


def _churn(store, rng, step):
    """One randomized mutation batch: adds, deletes, node flaps, queue
    weight edits."""
    op = rng.choice(["add_gang", "delete_pod", "node_flap",
                     "queue_weight", "add_pods"])
    if op == "add_gang":
        name = f"churn-{step}"
        store.add_pod_group(PodGroup(name=name, min_member=2))
        for i in range(2):
            store.add_pod(Pod(
                name=f"{name}-{i}",
                annotations={GROUP_NAME_ANNOTATION: name},
                containers=[{"cpu": "1", "memory": "1Gi"}],
            ))
    elif op == "delete_pod":
        # Keyed by NAME: uids are process-global counters, so a twin
        # run's uids differ and must not steer the op sequence.
        pods = sorted(store.pods.values(), key=lambda p: p.name)
        if pods:
            store.delete_pod(pods[rng.randrange(len(pods))])
    elif op == "node_flap":
        names = sorted(store.mirror.n_row)
        if names:
            name = names[rng.randrange(len(names))]
            if rng.random() < 0.5:
                store.delete_node(name)
            else:
                store.add_node(Node(
                    name=name,
                    allocatable={"cpu": "64", "memory": "256Gi",
                                 "pods": 256},
                ))
    elif op == "queue_weight":
        store.update_queue(Queue(name="default",
                                 weight=rng.randrange(1, 9)))
    elif op == "add_pods":
        name = f"solo-{step}"
        store.add_pod_group(PodGroup(name=name, min_member=1))
        store.add_pod(Pod(
            name=f"{name}-0",
            annotations={GROUP_NAME_ANNOTATION: name},
            containers=[{"cpu": "2", "memory": "2Gi"}],
        ))


def test_churn_parity_aggregates_order_and_binds(monkeypatch):
    """Randomized churn: after every cycle the persistent aggregates,
    the merged job rank, AND the end-to-end binds are bit-for-bit equal
    to the full-rebuild path (a twin store with the incremental
    machinery off sees the identical op sequence)."""
    monkeypatch.setenv("VOLCANO_TPU_INCR_VERIFY", "1")

    def run(incremental: bool):
        monkeypatch.setenv("VOLCANO_TPU_INCREMENTAL",
                           "1" if incremental else "0")
        _reset_uid_counters()
        store = synthetic_cluster(
            n_nodes=10, n_pods=48, gang_size=4, zones=2, n_queues=2,
            queue_weights=(1, 3), affinity_fraction=0.2,
            anti_affinity_fraction=0.1, spread_fraction=0.2, seed=3,
        )
        sched = Scheduler(store, conf_str=CONF)
        rng = random.Random(11)
        modes = []
        for step in range(8):
            sched.run_once()
            modes.append(store.mirror._cycle_aggr.last_mode)
            if incremental:
                _assert_aggr_parity(store)
                _assert_rank_parity(store)
            _churn(store, rng, step)
        sched.run_once()
        binds = dict(store.binder.binds)
        phases = {uid: pg.status.phase
                  for uid, pg in sorted(store.pod_groups.items())}
        status = {
            store.mirror.p_uid[r]: (
                int(store.mirror.p_status[r]),
                store.mirror.p_node_name[r],
            )
            for r in range(store.mirror.n_pods)
            if store.mirror.p_uid[r] is not None
        }
        return binds, phases, status, modes

    binds_on, phases_on, status_on, modes_on = run(True)
    binds_off, phases_off, status_off, modes_off = run(False)
    assert binds_on == binds_off
    assert phases_on == phases_off
    assert status_on == status_off
    # The incremental run must actually take the delta path (node flaps
    # force some full rebuilds; steady steps must not).
    assert "delta" in modes_on
    assert all(mode == "full" for mode in modes_off)


def test_rank_merge_matches_full_lexsort():
    """rank_from_cols: merged ranks are identical to the full lexsort
    under randomized key churn (unique tie-break column)."""
    from volcano_tpu.fastpath_incr import rank_from_cols

    rng = np.random.default_rng(5)
    n = 257
    prio = rng.integers(0, 4, n)
    gang = rng.integers(0, 2, n).astype(bool)
    drf = rng.random(n).astype(np.float64)
    create = rng.random(n)
    uid_rank = rng.permutation(n).astype(np.int64)
    cache = None
    for step in range(30):
        cols = [prio.copy(), gang.copy(), drf.copy(), create.copy(),
                uid_rank]
        rank, cache = rank_from_cols(cols, cache)
        order = np.lexsort(tuple(reversed(cols)))
        want = np.empty(n, np.int64)
        want[order] = np.arange(n)
        assert np.array_equal(rank, want), f"step {step}"
        # Perturb a few rows' keys for the next iteration.
        k = int(rng.integers(0, 9))
        idx = rng.choice(n, size=k, replace=False).astype(np.int64)
        prio[idx] = rng.integers(0, 4, k)
        drf[idx] = rng.random(k)


def test_encode_cache_bit_for_bit(monkeypatch):
    """The cached encode-lane structures (profiles, pid, affinity
    inputs) must be bit-identical to a cache-free rebuild — including
    the inter-pod term path."""
    monkeypatch.setenv("VOLCANO_TPU_INCREMENTAL", "1")
    store = synthetic_cluster(
        n_nodes=6, n_pods=24, gang_size=4, zones=2,
        affinity_fraction=0.4, anti_affinity_fraction=0.2,
        spread_fraction=0.4, seed=1,
    )
    _inqueue_all(store)
    cyc = _probe(store)
    with store._lock:
        ordered = cyc._ordered_jobs()
        prep = cyc._pending_rows(ordered)
        assert prep is not None
        solve_jobs, task_rows = prep
        store._encode_cache = None
        built = cyc._solve_inputs(solve_jobs, task_rows, slim=True)
        assert store._encode_cache is not None
        cached = cyc._solve_inputs(solve_jobs, task_rows, slim=True)

    def eq(a, b, path="root"):
        if isinstance(a, np.ndarray):
            assert np.array_equal(a, np.asarray(b)), path
        elif isinstance(a, (list, tuple)):
            assert len(a) == len(list(b)), path
            for i, (x, y) in enumerate(zip(a, b)):
                eq(x, y, f"{path}[{i}]")
        elif hasattr(a, "_fields"):  # NamedTuple
            for f in a._fields:
                eq(getattr(a, f), getattr(b, f), f"{path}.{f}")
        else:
            assert a == b, path

    (inputs_b, pid_b, profiles_b, _ncls_b) = built
    (inputs_c, pid_c, profiles_c, _ncls_c) = cached
    eq(pid_b, pid_c, "pid")
    eq(profiles_b, profiles_c, "profiles")
    # nodes/tasks/jobs/queues/weights/eps/scalar/aff
    for i, (x, y) in enumerate(zip(inputs_b, inputs_c)):
        if hasattr(x, "_fields"):
            for f in x._fields:
                a_f, b_f = getattr(x, f), getattr(y, f)
                if isinstance(a_f, np.ndarray):
                    eq(a_f, b_f, f"inputs[{i}].{f}")
        elif isinstance(x, np.ndarray):
            eq(x, y, f"inputs[{i}]")


def test_pending_rows_cache_reused_and_invalidated(monkeypatch):
    monkeypatch.setenv("VOLCANO_TPU_INCREMENTAL", "1")
    store = synthetic_cluster(n_nodes=6, n_pods=24, gang_size=3, seed=2)
    _inqueue_all(store)
    cyc = _probe(store)
    with store._lock:
        ordered = cyc._ordered_jobs()
        a = cyc._pending_rows(ordered)
        b = cyc._pending_rows(ordered)
    assert a is not None and b is not None
    # Second call reuses the cached (frozen) task-row array.
    assert b[1] is a[1]
    assert a[0] == b[0]
    # A status change invalidates via the pending-set content.
    row = int(a[1][0])
    with store._lock:
        store.mirror.p_status[row] = int(TaskStatus.Bound)
        store.mirror.mark_pods_dirty(np.array([row]))
        store.mirror.mutation_seq += 1
    cyc2 = _probe(store)
    with store._lock:
        ordered2 = cyc2._ordered_jobs()
        c = cyc2._pending_rows(ordered2)
    assert c is not None
    assert row not in c[1]


def test_dirty_cap_overflow_falls_back(monkeypatch):
    """Past VOLCANO_TPU_DIRTY_CAP the tracker gives up and the next
    derive full-rebuilds — with identical results."""
    monkeypatch.setenv("VOLCANO_TPU_INCREMENTAL", "1")
    monkeypatch.setenv("VOLCANO_TPU_DIRTY_CAP", "2")
    store = synthetic_cluster(n_nodes=6, n_pods=24, gang_size=2, seed=4)
    sched = Scheduler(store, conf_str=CONF)
    sched.run_once()  # first derive: full (no prior state)
    # The commit marked ~24 rows > cap 2 -> overflow -> next derive full.
    m = store.mirror
    assert m._pod_dirty_overflow
    sched.run_once()
    aggr = m._cycle_aggr
    assert aggr.last_mode == "full"
    assert aggr.full_reason == "dirty-overflow"
    _assert_aggr_parity(store)


def test_dirty_set_and_staleness_guard_agree(caplog):
    """Every mutation batch that advances the dirty set also advances
    mutation_seq (or epoch / compact_gen) — the agreement the pipelined
    staleness guard's skip-on-equality proof rests on.  Exercised over
    randomized store ops AND a pipelined loop with mid-flight
    mutations; the defensive revalidation path must never fire."""
    store = synthetic_cluster(n_nodes=8, n_pods=32, gang_size=2, seed=6)
    m = store.mirror
    rng = random.Random(13)
    sched = Scheduler(store, conf_str=CONF)
    store.pipeline = True

    def token():
        return (m.mutation_seq, m.dirty_seq, m.epoch, m.compact_gen)

    with caplog.at_level(logging.ERROR, logger="volcano_tpu.fastpath"):
        prev = token()
        for step in range(10):
            sched.run_once()
            _churn(store, rng, 100 + step)
            cur = token()
            if cur[1] != prev[1]:  # dirty_seq advanced ...
                assert (cur[0] != prev[0] or cur[2] != prev[2]
                        or cur[3] != prev[3]), (
                    "dirty set advanced without mutation_seq/epoch/"
                    "compact_gen")
            prev = cur
        sched.run_once()
    assert "without a mutation_seq bump" not in caplog.text


def test_live_status_counts_match_scan():
    """Close-time live counts (derive table + current dirty deltas)
    equal a full scan after in-cycle mutations."""
    from volcano_tpu.fastpath_incr import (
        _scan_status_counts,
        aggregates_of,
    )

    store = synthetic_cluster(n_nodes=4, n_pods=16, gang_size=2, seed=8)
    cyc = _probe(store)
    m = store.mirror
    with store._lock:
        # Mutate a few rows the way a commit would (status writes +
        # dirty marks, no derive in between).
        rows = np.array([0, 3, 5], np.int64)
        m.p_status[rows] = int(TaskStatus.Bound)
        m.p_node[rows] = 0
        m.mark_pods_dirty(rows)
        m.mutation_seq += 1
        live = aggregates_of(m).live_status_counts(m, cyc.Pn)
        want = _scan_status_counts(m, cyc.Pn, len(m.j_uid))
    assert np.array_equal(live, want)


def test_close_gauge_cache_reuses_retry_keys(monkeypatch):
    """A persistently-unready gang re-increments its retry counter each
    cycle from the CACHED key list (no per-cycle rebuild), with gauge
    values unchanged."""
    from volcano_tpu.metrics import metrics

    monkeypatch.setenv("VOLCANO_TPU_INCREMENTAL", "1")
    store = synthetic_cluster(n_nodes=2, n_pods=4, gang_size=4, seed=9,
                              pod_cpu_choices=("512",))  # can't fit
    sched = Scheduler(store, conf_str=CONF)
    sched.run_once()
    cache1 = store._close_gang_cache
    assert cache1 is not None
    key = cache1["retry_keys"][0]
    before = metrics.job_retry_counts.data.get(key, 0)
    sched.run_once()
    # Cache object survived (reused, not rebuilt) ...
    assert store._close_gang_cache is cache1
    # ... and the retry counter still advanced.
    assert metrics.job_retry_counts.data.get(key, 0) == before + 1


def test_incremental_env_kill_switch(monkeypatch):
    """VOLCANO_TPU_INCREMENTAL=0: every derive is a full rebuild and no
    host-lane cache is consulted."""
    monkeypatch.setenv("VOLCANO_TPU_INCREMENTAL", "0")
    store = synthetic_cluster(n_nodes=4, n_pods=12, gang_size=2, seed=10)
    sched = Scheduler(store, conf_str=CONF)
    sched.run_once()
    sched.run_once()
    aggr = store.mirror._cycle_aggr
    assert aggr.last_mode == "full"
    assert aggr.full_reason == "disabled"
    assert store._job_rank_cache is None
    assert store._pending_order_cache is None
    assert store._encode_cache is None
    assert store._objarr_cache is None
    assert store._unbind_gather_cache is None
    assert store._close_gang_cache is None


def test_node_heartbeat_keeps_delta_path(monkeypatch):
    """A content-identical node re-upsert (the controller heartbeat
    pattern) must NOT force the full-rebuild fallback: the aggregates
    key on node LIVENESS, not the full epoch — only an actual
    membership flip (remove/rejoin) invalidates."""
    monkeypatch.setenv("VOLCANO_TPU_INCREMENTAL", "1")
    monkeypatch.setenv("VOLCANO_TPU_INCR_VERIFY", "1")
    store = synthetic_cluster(n_nodes=4, n_pods=8, gang_size=2, seed=12)
    sched = Scheduler(store, conf_str=CONF)
    sched.run_once()
    # Heartbeat: re-upsert an existing, alive node unchanged.
    m = store.mirror
    store.add_node(Node(
        name=m.n_name[0],
        allocatable={"cpu": "64", "memory": "256Gi", "pods": 256},
    ))
    sched.run_once()
    assert store.mirror._cycle_aggr.last_mode == "delta"
    # Membership flip: the node leaves — the fallback must fire.
    store.delete_node(m.n_name[1])
    sched.run_once()
    aggr = store.mirror._cycle_aggr
    assert aggr.last_mode == "full"
    _assert_aggr_parity(store)


def test_fractional_quantities_stay_exact(monkeypatch):
    """Fractional quantity SPECS round up to integral milli/bytes at
    ingestion (k8s Quantity semantics), so the float64 delta planes
    keep their bit-for-bit contract — the runtime verifier must stay
    silent across delta derives with such pods."""
    monkeypatch.setenv("VOLCANO_TPU_INCREMENTAL", "1")
    monkeypatch.setenv("VOLCANO_TPU_INCR_VERIFY", "1")
    store = synthetic_cluster(n_nodes=4, n_pods=8, gang_size=2, seed=14)
    store.add_pod_group(PodGroup(name="frac", min_member=2))
    for t in range(2):
        store.add_pod(Pod(
            name=f"frac-{t}",
            annotations={GROUP_NAME_ANNOTATION: "frac"},
            # Numeric fractional cpu + sub-byte memory string: both
            # must land as integral quantities.
            containers=[{"cpu": 0.0001, "memory": "100m"}],
        ))
    from volcano_tpu.api.resource import parse_bytes, parse_milli

    assert parse_milli(0.0001) == 1.0
    assert parse_bytes("100m") == 1.0
    sched = Scheduler(store, conf_str=CONF)
    for _ in range(3):
        sched.run_once()  # INCR_VERIFY raises on any ulp drift
    _assert_aggr_parity(store)


def test_dirty_mask_growth_plants_no_stale_bits():
    """Mask growth must zero-fill: np.resize TILES the old contents,
    which would plant phantom dirty bits at rows beyond the table."""
    store = synthetic_cluster(n_nodes=2, n_pods=4, gang_size=1, seed=13)
    m = store.mirror
    with store._lock:
        m.consume_pod_dirty(m.n_pods)  # reset
        cap = len(m._pod_dirty_mask)
        m.mark_pod_dirty(0)
        m.mark_pod_dirty(cap + 5)  # forces growth with bit 0 set
        mask = m._pod_dirty_mask
        assert mask[0] and mask[cap + 5]
        assert int(mask.sum()) == 2, "growth tiled stale bits"
