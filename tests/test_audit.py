"""Runtime conservation auditor + SLO layer (ISSUE 13).

Pins the acceptance contracts of obs/audit.py and obs/slo.py:

- a clean churn run (binds, unbinds, deletes, adds, compactions)
  produces ZERO anomalies with the auditor sampling every cycle;
- each anomaly class, seeded deliberately, is detected within <= 2
  cycles with its exact catalogued reason, increments
  ``volcano_audit_anomalies_total``, lands in the cycle's flight
  record and in ``/debug/anomalies``, and shows in ``/debug/health``;
- ``/debug/health`` never blocks the cycle thread: it answers while
  another thread HOLDS the store lock (the non-blocking contract);
- the Perfetto export emits an instant event per anomaly.

All CPU-only (conftest pins JAX_PLATFORMS=cpu); tier-1.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Pod,
    PodGroup,
    TaskStatus,
)
from volcano_tpu.metrics import metrics
from volcano_tpu.obs import export
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.synth import synthetic_cluster

pytestmark = pytest.mark.tier1

ST_BOUND = int(TaskStatus.Bound)
ST_PENDING = int(TaskStatus.Pending)


@pytest.fixture(autouse=True)
def _dense_sampling(monkeypatch):
    """Audit every cycle: the seeded-corruption contracts are
    '<= 2 cycles to detection', which needs the sample gate open."""
    monkeypatch.setenv("VOLCANO_TPU_AUDIT_SAMPLE", "1")


def _churn_store(n_nodes=16, n_pods=64, frac=3):
    store = synthetic_cluster(n_nodes=n_nodes, n_pods=n_pods,
                              gang_size=4, seed=3)
    store.pipeline = True

    def feed(fc):
        m = fc.m
        rows = np.flatnonzero(
            (m.p_status[:fc.Pn] == ST_BOUND) & m.p_alive[:fc.Pn]
        )
        if len(rows):
            fc._unbind_rows(rows[:max(1, len(rows) // frac)])

    store.cycle_feed = feed
    return store


def _anomaly_metric(reason):
    return metrics.audit_anomalies.data.get((("reason", reason),), 0.0)


# --------------------------------------------------------- clean runs


def test_clean_churn_run_has_zero_anomalies():
    """Sustained bind/unbind churn plus store-edge add/delete churn,
    audited every cycle, reconciles clean — the endurance gate's
    baseline invariant."""
    store = _churn_store()
    sched = Scheduler(store)
    sched.run_once()
    sched.run_once()  # pipeline fill: first commit lands
    store.flush_binds()
    # Store-edge churn: delete one bound pod, add a fresh one.
    victim = next(p for p in store.pods.values() if p.node_name)
    store.delete_pod(victim)
    store.add_pod_group(PodGroup(name="fresh", min_member=1))
    store.add_pod(Pod(name="fresh-0",
                      annotations={GROUP_NAME_ANNOTATION: "fresh"},
                      containers=[{"cpu": "1", "memory": "1Gi"}]))
    for _ in range(6):
        sched.run_once()
    store.flush_binds()
    a = store.auditor
    assert a.total_anomalies() == 0, [
        x.to_dict() for x in a.anomalies()]
    stats = a.audit_stats()
    assert stats["reconciles"] >= 6
    assert stats["sampled_cycles"] >= 6
    # Flows were actually declared (double-entry, not vacuous).
    health = a.health()
    assert health["status"] == "ok"
    assert health["flow_totals"].get("commit-bind", 0) > 0
    assert health["flow_totals"].get("unbind", 0) > 0
    assert health["flow_totals"].get("pod-deleted", 0) >= 1
    assert health["flow_totals"].get("pod-added", 0) >= 1
    assert health["verifiers"]["audit"] is True
    store.close()


def test_idle_cycles_skip_census():
    """An idle store (no flows, unmoved mutation_seq) skips the census
    on unsampled cycles — the null-delta cost contract."""
    import os

    os.environ["VOLCANO_TPU_AUDIT_SAMPLE"] = "64"
    store = synthetic_cluster(n_nodes=4, n_pods=8, gang_size=2, seed=5)
    assert store.auditor.sample == 64
    sched = Scheduler(store)
    for _ in range(5):
        sched.run_once()
    store.flush_binds()
    stats = store.auditor.audit_stats()
    assert stats["census_skips"] >= 1
    assert store.auditor.total_anomalies() == 0
    store.close()


# ----------------------------------------------- seeded anomaly classes


def test_seeded_conservation_mismatch():
    """A silent status flip (no flow, no mutation stamp) surfaces as
    conservation-mismatch within <= 2 cycles, with the per-class diff
    in the detail, the metrics counter bumped, and the anomaly in the
    cycle's flight record."""
    store = _churn_store()
    sched = Scheduler(store)
    for _ in range(3):
        sched.run_once()
    assert store.auditor.total_anomalies() == 0
    before = _anomaly_metric("conservation-mismatch")
    m = store.mirror
    n = len(m.p_uid)
    rows = np.flatnonzero(m.p_alive[:n] & (m.p_status[:n] == ST_BOUND))
    m.p_status[rows[0]] = ST_PENDING  # the silent corruption
    sched.run_once()
    sched.run_once()
    counts = dict(store.auditor.anomaly_counts)
    assert counts.get("conservation-mismatch", 0) >= 1, counts
    assert _anomaly_metric("conservation-mismatch") > before
    anom = next(a for a in store.auditor.anomalies()
                if a.reason == "conservation-mismatch")
    assert anom.detail["classes"], anom.detail
    # The cycle that detected it carries it in its flight record.
    assert any(
        any(d["reason"] == "conservation-mismatch"
            for d in rec.anomalies)
        for rec in store.flight.recent()
    )
    store.close()


def test_seeded_aggregate_plane_corruption():
    """Corrupting one persistent aggregate cell surfaces as
    aggregate-divergence at the next sampled derive (<= 2 cycles)."""
    store = _churn_store()
    sched = Scheduler(store)
    for _ in range(3):
        sched.run_once()
    assert store.auditor.total_anomalies() == 0
    store.mirror._cycle_aggr.n_used[0, 0] += 5.0
    sched.run_once()
    sched.run_once()
    counts = dict(store.auditor.anomaly_counts)
    assert counts.get("aggregate-divergence", 0) >= 1, counts
    anom = next(a for a in store.auditor.anomalies()
                if a.reason == "aggregate-divergence")
    assert "n_used" in anom.detail["message"]
    store.close()


def test_seeded_ledger_restore_drop():
    """Dropping a migration restore (the pod_deleted hook bypassed)
    surfaces as ledger-restore-lost naming the victim."""
    from volcano_tpu.actions.rebalance import MigrationLedger

    store = _churn_store()
    sched = Scheduler(store)
    sched.run_once()
    sched.run_once()  # pipeline fill: first commit lands
    store.flush_binds()
    victim = next(p for p in store.pods.values() if p.node_name)
    gang = (victim.annotations or {}).get(GROUP_NAME_ANNOTATION)
    ledger = store.migrations = MigrationLedger()
    ledger.register(victim.uid, f"default/{gang}", "", action="preempt")
    # The corruption: terminate the victim with the restore hook dead.
    ledger.pod_deleted = lambda *a, **kw: None
    victim.deleting = True
    store.delete_pod(victim)
    sched.run_once()
    counts = dict(store.auditor.anomaly_counts)
    assert counts.get("ledger-restore-lost", 0) >= 1, counts
    anom = next(a for a in store.auditor.anomalies()
                if a.reason == "ledger-restore-lost")
    assert anom.detail["victim"] == victim.uid
    store.close()


class _CycStub:
    """The end_cycle surface of a FastCycle, for audit passes driven
    between real cycles (the cycle itself would re-dispatch and move
    the very wire generation the seed corrupts)."""

    def __init__(self, store):
        self.store = store
        self.m = store.mirror
        self.stats = {"dispatched_solve_id": None}
        self.lanes = {}


def _wire_store():
    """A store whose solves really ship over loopback TCP, so the wire
    mirror the audit guards is the production one."""
    import threading

    from volcano_tpu.solver_service import RemoteSolver, SolverServer

    store = _churn_store(n_nodes=8, n_pods=16)
    server = SolverServer(port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    client = RemoteSolver(f"127.0.0.1:{server.port}")
    store.remote_solver = client
    sched = Scheduler(store)
    for _ in range(3):
        sched.run_once()  # real frames ship; the sentinel anchors
    store.flush_binds()
    assert client._wire.arrays is not None, "no wire mirror to audit"
    assert store.auditor.total_anomalies() == 0
    return store, server, client


def test_seeded_wire_generation_skew():
    """A wire-mirror generation regression surfaces as
    wire-mirror-divergence (kind=key-regressed), through the real
    end_cycle pathway (ring + counter)."""
    store, server, client = _wire_store()
    before = _anomaly_metric("wire-mirror-divergence")
    client._gen -= 1  # the corruption: generation went backward
    anoms = store.auditor.end_cycle(_CycStub(store), 0.01)
    assert [a.reason for a in anoms] == ["wire-mirror-divergence"]
    assert anoms[0].detail["kind"] == "key-regressed"
    assert _anomaly_metric("wire-mirror-divergence") > before
    assert any(a.reason == "wire-mirror-divergence"
               for a in store.auditor.anomalies())
    client.close()
    server.shutdown()
    store.close()


def test_seeded_wire_mirror_mutation():
    """Mirror bytes changing under a HELD generation (the delta-frame
    poison) surface as wire-mirror-divergence."""
    store, server, client = _wire_store()
    # Anchor the sentinel at the current (gen, content) pair.
    assert store.auditor.end_cycle(_CycStub(store), 0.01) == []
    arr = client._wire.arrays[0]
    arr.reshape(-1)[0] += 1  # in-place mutation, same gen
    anoms = store.auditor.end_cycle(_CycStub(store), 0.01)
    assert [a.reason for a in anoms] == ["wire-mirror-divergence"]
    assert anoms[0].detail["kind"] == "content-changed-under-key"
    client.close()
    server.shutdown()
    store.close()


def test_replaced_wire_client_reanchors_not_regresses():
    """Solver failover to a FRESH client (generation restarts at 0)
    must re-anchor the wire sentinel, not read as a generation
    regression — client replacement is recovery, not corruption."""
    from volcano_tpu.solver_service import RemoteSolver

    store, server, client = _wire_store()
    assert client._gen > 0
    fresh = RemoteSolver(f"127.0.0.1:{server.port}")
    store.remote_solver = fresh  # failover: brand-new client, gen 0
    assert store.auditor.end_cycle(_CycStub(store), 0.01) == []
    assert store.auditor.end_cycle(_CycStub(store), 0.01) == []
    assert store.auditor.total_anomalies() == 0
    fresh.close()
    client.close()
    server.shutdown()
    store.close()


def test_seeded_slo_budget_breach():
    """An impossible declared budget breaches once the window fills:
    exact reason, burn-rate gauge set, breach visible in
    /debug/health's slo section, and re-emitted only on the edge."""
    from volcano_tpu.obs.slo import MIN_SAMPLES

    store = _churn_store()
    store.auditor.slo.declare("cycle", 0.0001, allowed_frac=0.001)
    sched = Scheduler(store)
    for _ in range(MIN_SAMPLES + 2):
        sched.run_once()
    counts = dict(store.auditor.anomaly_counts)
    assert counts.get("slo-budget-exceeded", 0) == 1, counts
    anom = next(a for a in store.auditor.anomalies()
                if a.reason == "slo-budget-exceeded")
    assert anom.detail["lane"] == "cycle"
    assert anom.detail["burn_rate"] >= 1.0
    health = store.auditor.health()
    lane = health["slo"]["cycle"]
    assert lane["breached"] is True
    assert lane["budget_remaining"] == 0.0
    assert metrics.slo_burn_rate.data[(("lane", "cycle"),)] >= 1.0
    store.close()


def test_seeded_encode_cache_mutation():
    """In-place mutation of the encode cache's arrays under a held key
    surfaces as cache-content-mutated naming the slot."""
    store = synthetic_cluster(n_nodes=4, n_pods=8, gang_size=2, seed=5)
    store.pipeline = True
    sched = Scheduler(store)
    sched.run_once()
    # Pin an unschedulable pending pod so the encode cache persists
    # with a stable key across idle cycles (the null-probe idiom).
    store.add_pod_group(PodGroup(name="probe", min_member=1))
    store.add_pod(Pod(
        name="probe-0", annotations={GROUP_NAME_ANNOTATION: "probe"},
        containers=[{"cpu": "900000", "memory": "900000Gi"}],
    ))
    for _ in range(3):
        sched.run_once()
    cached = store._encode_cache
    assert cached is not None
    assert store.auditor.total_anomalies() == 0
    cached["pid"][0] += 1  # the corruption
    sched.run_once()
    sched.run_once()
    counts = dict(store.auditor.anomaly_counts)
    assert counts.get("cache-content-mutated", 0) >= 1, counts
    anom = next(a for a in store.auditor.anomalies()
                if a.reason == "cache-content-mutated")
    assert anom.detail["slot"] == "encode"
    store.close()


# ------------------------------------------------------ /debug surface


def test_debug_health_and_anomalies_endpoints_never_block():
    """/debug/health and /debug/anomalies serve while another thread
    HOLDS the store lock mid-churn — the handlers read only the
    auditor's own snapshots, so a scrape can never stall the cycle."""
    from volcano_tpu.service import Service

    store = _churn_store()
    sched = Scheduler(store)
    for _ in range(3):
        sched.run_once()
    # Seed one anomaly so the ring serves real content.
    m = store.mirror
    n = len(m.p_uid)
    rows = np.flatnonzero(m.p_alive[:n] & (m.p_status[:n] == ST_BOUND))
    m.p_status[rows[0]] = ST_PENDING
    sched.run_once()

    svc = Service(store=store, schedule_period=30.0,
                  controller_period=5.0)
    port = svc.start(http_port=0)
    try:
        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=10) as r:
                return json.loads(r.read())

        # Scrape WITH the store lock held elsewhere: must not block.
        result = {}
        with store._lock:
            t = threading.Thread(
                target=lambda: result.update(get("/debug/health")))
            t.start()
            t.join(timeout=5)
            assert not t.is_alive(), \
                "/debug/health blocked on the store lock"
        assert result["status"] == "anomalous"
        assert result["anomalies_by_reason"].get(
            "conservation-mismatch", 0) >= 1
        assert result["audit"]["cycles"] >= 4
        assert "verifiers" in result and "slo" in result

        ring = get("/debug/anomalies")
        assert any(a["reason"] == "conservation-mismatch" for a in ring)
        assert get("/debug/anomalies?n=1")[-1]["reason"] == \
            ring[-1]["reason"]

        # The detecting cycle's record serializes its anomalies.
        cycles = get("/debug/cycles")
        flagged = [c for c in cycles if c["anomalies"]]
        assert flagged, "no cycle record carries the anomaly"
        flag_seq = next(c["seq"] for c in cycles
                        if any(d["reason"] == "conservation-mismatch"
                               for d in c["anomalies"]))
        one = get(f"/debug/cycles/{flag_seq}")
        assert any(d["reason"] == "conservation-mismatch"
                   for d in one["anomalies"])
        # The ring entry cross-references its flight cycle: an operator
        # can walk /debug/anomalies -> /debug/cycles/<seq>.
        ring_seqs = {a["cycle_seq"] for a in ring
                     if a["reason"] == "conservation-mismatch"}
        assert flag_seq in ring_seqs, (ring, flag_seq)
    finally:
        svc.stop()
        store.close()


def test_perfetto_export_emits_anomaly_instants():
    """Every recorded anomaly becomes one instant event on the trace
    timeline (cat=audit, name=anomaly:<reason>)."""
    store = _churn_store()
    sched = Scheduler(store)
    for _ in range(3):
        sched.run_once()
    m = store.mirror
    n = len(m.p_uid)
    rows = np.flatnonzero(m.p_alive[:n] & (m.p_status[:n] == ST_BOUND))
    m.p_status[rows[0]] = ST_PENDING
    sched.run_once()
    trace = export.perfetto_trace(store.flight.recent())
    instants = [e for e in trace["traceEvents"]
                if e.get("cat") == "audit" and e.get("ph") == "i"]
    assert instants, "no anomaly instant in the export"
    assert any(e["name"] == "anomaly:conservation-mismatch"
               for e in instants)
    json.dumps(trace)  # round-trips as JSON
    store.close()


def test_audit_disable_and_reenable_reanchors():
    """VOLCANO_TPU_AUDIT A/B seam: disabling records nothing; the
    re-enable re-anchors so unrecorded mutations never read as a
    phantom conservation mismatch."""
    store = _churn_store()
    sched = Scheduler(store)
    sched.run_once()
    store.auditor.set_enabled(False)
    sched.run_once()  # churn with no flow bookkeeping
    store.auditor.set_enabled(True)
    sched.run_once()
    sched.run_once()
    assert store.auditor.total_anomalies() == 0, [
        x.to_dict() for x in store.auditor.anomalies()]
    store.close()
