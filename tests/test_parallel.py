"""Multi-chip solver sharding over a virtual 8-device CPU mesh.

Mirrors the driver's dryrun: node axis sharded via jax.sharding.Mesh +
NamedSharding, task/job/queue state replicated, GSPMD inserting the
cross-chip collectives (SURVEY.md 2.4 item 3 / section 7 design stance).
"""

import numpy as np
import pytest

import jax


needs_8 = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def _args(n_nodes=64, n_pods=64):
    from volcano_tpu.synth import solve_args_from_store, synthetic_cluster

    store = synthetic_cluster(
        n_nodes=n_nodes, n_pods=n_pods, gang_size=4, n_queues=2
    )
    args, _ = solve_args_from_store(store)
    return args


@needs_8
def test_sharded_sequential_solve_matches_single_device():
    from volcano_tpu.ops.allocate import solve
    from volcano_tpu.parallel import make_mesh, sharded_solve

    args = _args()
    mesh = make_mesh(8)
    sharded = np.asarray(sharded_solve(mesh, args).assigned)
    single = np.asarray(solve(*args).assigned)
    assert np.array_equal(sharded, single)
    assert (sharded >= 0).any()


@needs_8
def test_sharded_wave_solve_places_full_count():
    from volcano_tpu.ops.wave import solve_wave
    from volcano_tpu.parallel import make_mesh, sharded_solve_wave

    from test_wave import _check_invariants

    args = _args()
    mesh = make_mesh(8)
    res = sharded_solve_wave(mesh, args)
    sharded = np.asarray(res.assigned)
    single = np.asarray(solve_wave(*args).assigned)
    # Cross-shard reduction order may flip score near-ties; the placement
    # COUNT, oversubscription, and gang invariants must hold.
    assert (sharded >= 0).any()
    assert int((sharded >= 0).sum()) == int((single >= 0).sum())
    _check_invariants(args, res)


@needs_8
def test_mesh_sizes():
    from volcano_tpu.parallel import make_mesh, sharded_solve

    args = _args(n_nodes=16, n_pods=16)
    for n in (2, 4):
        mesh = make_mesh(n)
        assert mesh.devices.size == n
        out = np.asarray(sharded_solve(mesh, args).assigned)
        assert (out >= 0).any()


@needs_8
def test_sharded_wave_solve_with_sparse_cnt0(monkeypatch):
    """The on-device sparse cnt0 rebuild must respect the mesh caller's
    replicated sharding (committed-device compatibility)."""
    import volcano_tpu.ops.wave as wave
    from volcano_tpu.parallel import make_mesh, sharded_solve_wave

    monkeypatch.setattr(wave, "CNT0_SPARSE_MIN", 0)
    args = _args()
    mesh = make_mesh(8)
    res = sharded_solve_wave(mesh, args)
    assert (np.asarray(res.assigned) >= 0).any()


@needs_8
def test_full_cycle_on_mesh_with_sharded_count_tensors():
    """The COMPLETE fastpath cycle (enqueue -> allocate -> commit ->
    close) dispatched over the 8-device mesh via store.solve_mesh, with
    a required-affinity/anti/spread mix so cnt0 shards on the domain
    axis (parallel/mesh.py shard_wave_inputs — the hyperscale memory
    wall).  Bind-count parity with the single-device cycle; a mesh-path
    failure must raise, not silently fall back."""
    import os

    from volcano_tpu.parallel import make_mesh
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.synth import synthetic_cluster

    kw = dict(n_nodes=64, n_pods=128, gang_size=4, zones=4,
              affinity_fraction=0.25, anti_affinity_fraction=0.25,
              spread_fraction=0.25, seed=31)
    single = synthetic_cluster(**kw)
    Scheduler(single).run_once()
    single.flush_binds()

    meshed = synthetic_cluster(**kw)
    meshed.solve_mesh = make_mesh(8)
    os.environ["VOLCANO_TPU_FALLBACK"] = "never"
    try:
        Scheduler(meshed).run_once()
    finally:
        os.environ.pop("VOLCANO_TPU_FALLBACK", None)
    meshed.flush_binds()
    assert len(meshed.binder.binds) == len(single.binder.binds)
    assert len(meshed.binder.binds) == 128
    single.close()
    meshed.close()


@needs_8
def test_mesh_sparse_rebuild_sharded_cnt0(monkeypatch):
    """Sparse cnt0/profile-table rebuilds under a COLUMN-sharded mesh
    caller: the rebuilt [E+1, D] pair inherits the domain-axis sharding
    and the [U, Ep+1] tables fall back to replicated when the term axis
    does not divide (ops/wave.py rebuild fallback)."""
    import os

    import volcano_tpu.ops.wave as wave
    from volcano_tpu.parallel import make_mesh
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.synth import synthetic_cluster

    monkeypatch.setattr(wave, "CNT0_SPARSE_MIN", 0)
    monkeypatch.setattr(wave, "PROF_SPARSE_MIN", 0)
    store = synthetic_cluster(
        n_nodes=32, n_pods=64, gang_size=4, zones=4,
        affinity_fraction=0.5, anti_affinity_fraction=0.25, seed=13,
    )
    store.solve_mesh = make_mesh(8)
    os.environ["VOLCANO_TPU_FALLBACK"] = "never"
    try:
        Scheduler(store).run_once()
    finally:
        os.environ.pop("VOLCANO_TPU_FALLBACK", None)
    store.flush_binds()
    assert len(store.binder.binds) >= 60
    store.close()
