"""Worker process for the adversarial HA test (test_ha_persistence.py).

Runs a LeaderElector against a shared lease file; while leading, "binds"
pods by appending `<identity> <epoch> <pod-id>` lines to a shared
O_APPEND log — the side-effect channel standing in for cache.Bind, with
the lease's `acquired` timestamp as a fencing token.  Each cycle resyncs
from the log first (the informer-rebuild analog: a fresh leader continues
from the bound set, it does not restart it) and re-validates the lease
FILE (not a cached flag) immediately before the side effect, so a stalled
ex-leader that lost the lease cannot emit a stale bind — the same fencing
the reference gets from resourceVersion-checked updates
(cmd/scheduler/app/server.go leaderelection).

Usage: python ha_worker.py <lease_path> <log_path> <identity> <n_pods>
"""

import json
import os
import sys
import threading
import time


def main() -> None:
    lease_path, log_path, identity, n_pods = sys.argv[1:5]
    n_pods = int(n_pods)
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from volcano_tpu.ha import LeaderElector

    el = LeaderElector(
        lease_path,
        identity=identity,
        lease_duration=2.0,
        renew_deadline=1.5,
        retry_period=0.1,
    )
    t = threading.Thread(
        target=el.run, args=(lambda: None, lambda: None), daemon=True
    )
    t.start()

    def lease_epoch():
        """The fencing token: the `acquired` timestamp of the lease iff
        this process holds it right now, else None."""
        try:
            with open(lease_path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            return None
        if rec.get("holder") != identity:
            return None
        if time.time() >= float(rec.get("expiry", 0)):
            return None
        return rec.get("acquired")

    fd = os.open(log_path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
    while True:
        if not el.is_leader:
            time.sleep(0.02)
            continue
        # Resync: the bound set is rebuilt from the durable log, exactly
        # as a fresh reference leader rebuilds from the API server.
        try:
            with open(log_path) as f:
                bound = {
                    line.split()[2] for line in f if len(line.split()) == 3
                }
        except OSError:
            bound = set()
        nxt = next(
            (i for i in range(n_pods) if f"pod-{i}" not in bound), None
        )
        if nxt is None:
            time.sleep(0.05)
            continue
        # Mid-cycle work between resync and side effect — the window the
        # test's SIGKILL lands in.
        time.sleep(0.03)
        epoch = lease_epoch()  # fencing re-read just before the bind
        if epoch is not None:
            os.write(fd, f"{identity} {epoch} pod-{nxt}\n".encode())
        time.sleep(0.02)


if __name__ == "__main__":
    main()
