"""Every admission rule, table-driven.

The shape of ``pkg/webhooks/admission/jobs/validate/admit_job_test.go``
(1,242 LoC — the reference's second-largest test file) plus the queue
and pod admission tables (``validate_queue_test.go``,
``admit_pod_test.go``): one asserting case per rule, create and update.
Rules cite ``admit_job.go:107-196`` / ``util.go:161-183`` analogs in
``volcano_tpu/webhooks/admission.py``.
"""

import pytest

from volcano_tpu.api import GROUP_NAME_ANNOTATION, Pod, PodGroup, Queue
from volcano_tpu.cache import ClusterStore
from volcano_tpu.controllers import (
    Job,
    LifecyclePolicy,
    TaskSpec,
    VolumeSpec,
)
from volcano_tpu.webhooks.admission import (
    AdmissionError,
    mutate_job,
    validate_job_create,
    validate_job_update,
    validate_pod_create,
    validate_queue,
    validate_queue_delete,
)


@pytest.fixture()
def store():
    s = ClusterStore()
    s.add_queue(Queue(name="closed-q", weight=1, state="Closed"))
    return s


def base_job(**over):
    kw = dict(
        name="valid-job",
        min_available=1,
        tasks=[TaskSpec(name="task-1", replicas=1,
                        containers=[{"cpu": "1"}])],
    )
    kw.update(over)
    return Job(**kw)


def T(name, replicas=1, containers=({"cpu": "1"},), **kw):
    return TaskSpec(name=name, replicas=replicas,
                    containers=list(containers), **kw)


# (case name mirroring admit_job_test.go, job kwargs, expected message
#  fragment — None means the job must admit)
CREATE_CASES = [
    ("validate valid-job", {}, None),
    ("duplicate-task-job",
     dict(tasks=[T("duplicated-task-1"), T("duplicated-task-1")]),
     "duplicated task name"),
    ("nonDNS-task", dict(tasks=[T("Task_1")]), "must be DNS-1123"),
    ("replica-lessThanZero", dict(tasks=[T("task-1", replicas=-1)]),
     "'replicas' < 0"),
    ("no-task", dict(tasks=[]), "No task specified"),
    ("task-no-containers", dict(tasks=[T("task-1", containers=())]),
     "has no containers"),
    ("minAvailable-lessThanZero", dict(min_available=-1),
     "'minAvailable' must be > 0"),
    ("min-available-illegal",
     dict(min_available=2, tasks=[T("task-1", replicas=1)]),
     "'minAvailable' should not be greater than total replicas"),
    ("maxretry-lessThanZero", dict(max_retry=-1),
     "'maxRetry' cannot be less than zero"),
    ("job-ttl-illegal", dict(ttl_seconds_after_finished=-1),
     "'ttlSecondsAfterFinished' cannot be less than zero"),
    ("job-plugin-illegal", dict(plugins={"big-plugin": []}),
     "unable to find job plugin: big-plugin"),
    ("job-with-noQueue", dict(queue="jobQueue"),
     "unable to find job queue"),
    ("job-queue-not-open", dict(queue="closed-q"),
     "state `Open`"),
    # ---- policies (util.go validatePolicies) ----
    ("policy-event-with-exitcode",
     dict(policies=[LifecyclePolicy(action="AbortJob", event="PodFailed",
                                    exit_code=1)]),
     "must not specify event and exitCode simultaneously"),
    ("policy-noEvent-noExCode",
     dict(policies=[LifecyclePolicy(action="AbortJob")]),
     "either event or exitCode"),
    ("invalid-policy-action",
     dict(policies=[LifecyclePolicy(action="Terminate",
                                    event="PodFailed")]),
     "invalid policy action"),
    ("invalid-policy-event",
     dict(policies=[LifecyclePolicy(action="AbortJob",
                                    event="fakeEvent")]),
     "invalid policy event"),
    ("job-policy-duplicated",
     dict(policies=[
         LifecyclePolicy(action="AbortJob", event="PodFailed"),
         LifecyclePolicy(action="RestartJob", event="PodFailed"),
     ]),
     "duplicate event"),
    ("duplicate-exitcode",
     dict(policies=[
         LifecyclePolicy(action="AbortJob", exit_code=1),
         LifecyclePolicy(action="RestartJob", exit_code=1),
     ]),
     "duplicate exitCode"),
    ("policy-extcode-zero",
     dict(policies=[LifecyclePolicy(action="AbortJob", exit_code=0)]),
     "0 is not a valid error code"),
    ("policy-withAnyandOthrEvent",
     dict(policies=[
         LifecyclePolicy(action="AbortJob", events=["*", "PodFailed"]),
     ]),
     "no other policy should be here"),
    ("taskpolicy-withAnyandOthrEvent",
     dict(tasks=[T("task-1", policies=[
         LifecyclePolicy(action="AbortJob", events=["*", "PodEvicted"]),
     ])]),
     "no other policy should be here"),
    ("taskpolicy-duplicated",
     dict(tasks=[T("task-1", policies=[
         LifecyclePolicy(action="AbortJob", event="PodFailed"),
         LifecyclePolicy(action="RestartTask", event="PodFailed"),
     ])]),
     "duplicate event"),
    ("job-policy-valid-exitcode",
     dict(policies=[LifecyclePolicy(action="AbortJob", exit_code=3)]),
     None),
    # ---- volumes (util.go validateIO) ----
    ("invalid-mount-volume",
     dict(volumes=[VolumeSpec(mount_path="",
                              volume_claim_name="v1")]),
     "mountPath is required"),
    ("duplicate-mount-volume",
     dict(volumes=[
         VolumeSpec(mount_path="/var", volume_claim_name="v1"),
         VolumeSpec(mount_path="/var", volume_claim_name="v2"),
     ]),
     "duplicated mountPath"),
    ("volume-without-claim-and-name",
     dict(volumes=[VolumeSpec(mount_path="/var")]),
     "either volumeClaim or volumeClaimName"),
    ("volume-with-claim-and-name",
     dict(volumes=[VolumeSpec(mount_path="/var", volume_claim_name="v",
                              volume_claim={"storage": "1Gi"})]),
     "conflict"),
    ("volume-bad-claim-name",
     dict(volumes=[VolumeSpec(mount_path="/var",
                              volume_claim_name="Invalid_Claim")]),
     "invalid volumeClaimName"),
    ("volume-valid-pair",
     dict(volumes=[
         VolumeSpec(mount_path="/in", volume_claim={"storage": "1Gi"}),
         VolumeSpec(mount_path="/out", volume_claim_name="out-claim"),
     ]),
     None),
]


@pytest.mark.parametrize("name,kw,frag", CREATE_CASES,
                         ids=[c[0] for c in CREATE_CASES])
def test_job_create_rule(store, name, kw, frag):
    job = base_job(**kw)
    if frag is None:
        validate_job_create(job, store)
    else:
        with pytest.raises(AdmissionError) as ei:
            validate_job_create(job, store)
        assert frag in str(ei.value), f"{name}: {ei.value}"


# ---- update rules (admit_job.go:198-236) ----

def upd(old_over=None, new_over=None):
    def mk(over):
        kw = dict(min_available=1, tasks=[T("task-1", replicas=2)])
        kw.update(over or {})
        return base_job(**kw)

    return mk(old_over), mk(new_over)


UPDATE_CASES = [
    ("scale-replicas-ok", {}, dict(tasks=[T("task-1", replicas=5)]),
     None),
    ("raise-minavailable-ok", {}, dict(min_available=2), None),
    ("minavailable-above-total", {}, dict(min_available=3),
     "'minAvailable' must not be greater"),
    ("minavailable-zero", {}, dict(min_available=0),
     "'minAvailable' must be > 0"),
    ("negative-replicas", {}, dict(tasks=[T("task-1", replicas=-2)]),
     "'replicas' must be >= 0"),
    ("add-task", {}, dict(tasks=[T("task-1", replicas=2), T("task-2")]),
     "may not add or remove tasks"),
    ("rename-task", {}, dict(tasks=[T("task-x", replicas=2)]),
     "may not change fields"),
    ("change-containers", {},
     dict(tasks=[T("task-1", replicas=2, containers=({"cpu": "9"},))]),
     "may not change fields"),
    ("change-queue", {}, dict(queue="other"), "may not change fields"),
    ("change-plugins", {}, dict(plugins={"svc": []}),
     "may not change fields"),
    ("change-priorityclass", {}, dict(priority_class="high"),
     "may not change fields"),
    ("change-volumes", {},
     dict(volumes=[VolumeSpec(mount_path="/v",
                              volume_claim_name="c")]),
     "may not change fields"),
    ("generated-claim-name-normalized",
     dict(volumes=[VolumeSpec(mount_path="/v",
                              volume_claim={"storage": "1Gi"})]),
     dict(volumes=[VolumeSpec(mount_path="/v",
                              volume_claim={"storage": "1Gi"},
                              volume_claim_name="gen-abc123")]),
     None),
]


@pytest.mark.parametrize("name,old_over,new_over,frag", UPDATE_CASES,
                         ids=[c[0] for c in UPDATE_CASES])
def test_job_update_rule(name, old_over, new_over, frag):
    old, new = upd(old_over, new_over)
    if frag is None:
        validate_job_update(old, new)
    else:
        with pytest.raises(AdmissionError) as ei:
            validate_job_update(old, new)
        assert frag in str(ei.value), f"{name}: {ei.value}"


# ---- queue + pod admission (validate_queue_test.go / admit_pod.go) ----

def test_queue_rules():
    validate_queue(Queue(name="ok", weight=3))
    with pytest.raises(AdmissionError, match="state must be in"):
        validate_queue(Queue(name="bad", state="Halted"))
    with pytest.raises(AdmissionError, match="weight must be >= 0"):
        validate_queue(Queue(name="bad", weight=-1))
    with pytest.raises(AdmissionError, match="can not be deleted"):
        validate_queue_delete("default")
    validate_queue_delete("other")  # non-default deletes pass


def test_pod_gate_rules(store):
    # No group annotation: passes through.
    validate_pod_create(Pod(name="free"), store)
    # Unknown PodGroup: denied.
    pod = Pod(name="p", annotations={GROUP_NAME_ANNOTATION: "missing"})
    with pytest.raises(AdmissionError, match="failed to get PodGroup"):
        validate_pod_create(pod, store)
    # Pending PodGroup: denied until the scheduler moves it to Inqueue.
    store.add_pod_group(PodGroup(name="gate", min_member=1))
    pod2 = Pod(name="p2", annotations={GROUP_NAME_ANNOTATION: "gate"})
    with pytest.raises(AdmissionError, match="podgroup phase"):
        validate_pod_create(pod2, store)
    store.pod_groups["default/gate"].status.phase = "Inqueue"
    validate_pod_create(pod2, store)


def test_mutate_defaults():
    """mutate_job.go:74-111 defaulting table."""
    job = Job(name="m", min_available=1, queue="", scheduler_name="",
              max_retry=0, tasks=[T("task-1")])
    mutate_job(job)
    assert job.queue == "default"
    assert job.scheduler_name == "volcano-tpu"
    assert job.max_retry == 3
    # Set fields survive.
    job2 = Job(name="m2", min_available=1, queue="q", max_retry=5,
               tasks=[T("task-1")])
    mutate_job(job2)
    assert job2.queue == "q" and job2.max_retry == 5
