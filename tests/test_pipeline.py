"""Pipelined scheduler cycles (double-buffered sessions): overlap
correctness.

The pipelined cycle dispatches the device solve WITHOUT blocking and
commits the result at the top of the next cycle (ISSUE 1).  These tests
pin the overlap contracts: placement parity with the synchronous loop
when nothing moves during the overlap, the staleness guard dropping
exactly the conflicting rows when something does (pod deletes, competing
binds, capacity theft), clean drain/abandon of the in-flight solve on
stop/restart, whole-result invalidation across a mirror compaction, and
the device-resident snapshot's delta-upload path.

All of it runs under JAX_PLATFORMS=cpu (conftest forces the virtual CPU
platform) — no TPU required; the tier1 marker records that these belong
to the tier-1 overlap-correctness gate.
"""

import numpy as np
import pytest

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    TaskStatus,
)
from volcano_tpu.cache import ClusterStore
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.synth import synthetic_cluster

pytestmark = pytest.mark.tier1

ST_PENDING = int(TaskStatus.Pending)
ST_BOUND = int(TaskStatus.Bound)


def _placements(store):
    return {
        f"{p.namespace}/{p.name}": p.node_name
        for p in store.pods.values()
    }


def _assert_capacity_respected(store):
    """No node oversubscribed: sum of bound pods' cpu <= allocatable."""
    used = {}
    for p in store.pods.values():
        if p.node_name:
            req = p.resource_request()
            used[p.node_name] = used.get(p.node_name, 0) + req.milli_cpu
    for name, milli in used.items():
        node = next(n for n in store.mirror.node_objs
                    if n is not None and n.name == name)
        alloc = node.allocatable_resource()
        assert milli <= alloc.milli_cpu, f"{name} oversubscribed"


def _small(seed=7, **kw):
    kw.setdefault("n_nodes", 8)
    kw.setdefault("n_pods", 32)
    kw.setdefault("gang_size", 4)
    return synthetic_cluster(seed=seed, **kw)


# ------------------------------------------------------------- parity


def test_pipelined_matches_synchronous_without_mutations():
    """With no concurrent store mutations the pipelined loop lands the
    exact placements of the synchronous loop, one cycle later."""
    sync = _small()
    Scheduler(sync).run_once()
    sync.flush_binds()

    piped = _small()
    piped.pipeline = True
    sched = Scheduler(piped)
    sched.run_once()
    # Cycle 1 only dispatched: nothing bound yet, handle parked.
    assert piped._inflight_solve is not None
    assert len(piped.binder.binds) == 0
    sched.run_once()
    piped.flush_binds()
    assert piped._inflight_solve is None  # nothing left pending
    assert _placements(sync) == _placements(piped)
    assert len(piped.binder.binds) == len(sync.binder.binds)


def test_unmutated_overlap_skips_revalidation(monkeypatch):
    """mutation_seq equality at fetch proves nothing moved: the commit
    must take the fast path (no capacity re-validation)."""
    from volcano_tpu import fastpath

    store = _small()
    store.pipeline = True

    def boom(self, task_rows, assigned):
        raise AssertionError("revalidation ran on an unmutated overlap")

    monkeypatch.setattr(fastpath.FastCycle, "_revalidate_inflight", boom)
    sched = Scheduler(store)
    sched.run_once()
    sched.run_once()
    store.flush_binds()
    assert all(p.node_name for p in store.pods.values())


# ----------------------------------------------------- staleness guard


def _two_node_store(n_pods=4, node_cpu="2"):
    store = ClusterStore()
    for i in range(2):
        store.add_node(Node(
            name=f"n{i}",
            allocatable={"cpu": node_cpu, "memory": "8Gi", "pods": 64},
        ))
    pg = PodGroup(name="g", min_member=1)
    store.add_pod_group(pg)
    for k in range(n_pods):
        store.add_pod(Pod(
            name=f"p{k}",
            annotations={GROUP_NAME_ANNOTATION: pg.name},
            containers=[{"cpu": "1", "memory": "1Gi"}],
        ))
    return store


def test_overlap_delete_and_competing_bind_no_double_bind_no_lost_pod():
    """A pod deleted and a competing bind landing between dispatch N and
    fetch N: the deleted row and any row whose capacity was taken drop;
    every surviving pod binds exactly once; nothing is lost."""
    store = _two_node_store(n_pods=4, node_cpu="2")
    store.pipeline = True
    sched = Scheduler(store)
    sched.run_once()  # dispatch over the 4 pending pods
    assert store._inflight_solve is not None

    # Overlap mutations: delete p0; a competing scheduler binds a brand
    # new pod onto n0, eating one of the cpus the in-flight solve was
    # promised (a fast-path/async-bind race in production).
    victim = next(p for p in store.pods.values() if p.name == "p0")
    store.delete_pod(victim)
    intruder = Pod(
        name="intruder",
        annotations={GROUP_NAME_ANNOTATION: "g"},
        containers=[{"cpu": "1", "memory": "1Gi"}],
        node_name="n0",
    )
    store.add_pod(intruder)

    sched.run_once()  # fetch + staleness-guarded commit, then redispatch
    sched.run_once()  # land the redispatch of any dropped rows
    sched.run_once()
    store.flush_binds()

    live = [p for p in store.pods.values()]
    assert len(live) == 4  # 3 survivors + intruder
    # No lost pod: every live schedulable pod ends up bound.
    assert all(p.node_name for p in live)
    # No double bind: the async binder saw each surviving pod at most
    # once per final placement, and no node is oversubscribed.
    _assert_capacity_respected(store)
    m = store.mirror
    rows = [m.p_row[p.uid] for p in live]
    assert all(m.p_status[r] == ST_BOUND for r in rows)
    # Mirror column agrees with the records (batched column write).
    assert [m.p_node_name[r] for r in rows] == [p.node_name for p in live]


def test_overlap_full_capacity_theft_drops_rows_then_replaces():
    """Every cpu the in-flight solve counted on is stolen during the
    overlap: the guard must drop ALL rows targeting the stuffed nodes
    (no divergence error, no oversubscription) and later cycles re-place
    what still fits."""
    store = _two_node_store(n_pods=2, node_cpu="1")
    store.pipeline = True
    sched = Scheduler(store)
    sched.run_once()  # dispatch: p0 -> one node, p1 -> the other

    for i in range(2):
        store.add_pod(Pod(
            name=f"thief{i}",
            annotations={GROUP_NAME_ANNOTATION: "g"},
            containers=[{"cpu": "1", "memory": "1Gi"}],
            node_name=f"n{i}",
        ))
    sched.run_once()  # guard drops both rows; nothing commits
    store.flush_binds()
    originals = [p for p in store.pods.values()
                 if p.name.startswith("p")]
    assert all(p.node_name is None for p in originals)
    _assert_capacity_respected(store)
    m = store.mirror
    assert all(m.p_status[m.p_row[p.uid]] == ST_PENDING
               for p in originals)


def test_compaction_mid_flight_voids_whole_result():
    """Row renumbering (mirror compaction) between dispatch and fetch
    voids the in-flight result wholesale; the pods simply re-place."""
    store = _small(seed=9)
    store.pipeline = True
    sched = Scheduler(store)
    sched.run_once()
    assert store._inflight_solve is not None
    store.mirror.compact_gen += 1  # what maybe_compact() does
    sched.run_once()  # result dropped, fresh dispatch
    assert len(store.binder.binds) == 0
    sched.run_once()  # fresh result lands
    store.flush_binds()
    assert all(p.node_name for p in store.pods.values())


def test_node_relabel_mid_flight_drops_selector_rows():
    """Node labels changing during the overlap invalidate any in-flight
    row whose pod matched them via a nodeSelector: the solve saw stale
    planes, so the row drops (conservative) instead of committing a
    placement the synchronous loop could never produce."""
    store = ClusterStore()
    store.add_node(Node(
        name="gpu-node",
        allocatable={"cpu": "4", "memory": "8Gi", "pods": 16},
        labels={"gpu": "true"},
    ))
    store.add_node(Node(
        name="plain-node",
        allocatable={"cpu": "4", "memory": "8Gi", "pods": 16},
    ))
    store.add_pod_group(PodGroup(name="g", min_member=1))
    store.add_pod(Pod(
        name="needs-gpu",
        annotations={GROUP_NAME_ANNOTATION: "g"},
        containers=[{"cpu": "1", "memory": "1Gi"}],
        node_selector={"gpu": "true"},
    ))
    store.pipeline = True
    sched = Scheduler(store)
    sched.run_once()  # dispatch: the solve places needs-gpu on gpu-node
    assert store._inflight_solve is not None

    # Overlap mutation: the gpu label disappears (epoch bump).
    store.add_node(Node(
        name="gpu-node",
        allocatable={"cpu": "4", "memory": "8Gi", "pods": 16},
    ))
    sched.run_once()  # guard drops the selector row; fresh solve sees
    sched.run_once()  # no matching node
    store.flush_binds()
    pod = next(p for p in store.pods.values())
    assert pod.node_name is None, (
        "stale selector placement committed onto a relabelled node"
    )
    m = store.mirror
    assert m.p_status[m.p_row[pod.uid]] == ST_PENDING


def test_fetch_device_crash_degrades_budget_and_replaces(monkeypatch):
    """An execution-time device crash surfacing at the async fetch must
    route through the same chunk-budget degradation as a synchronous
    solve (not be swallowed), and the rows re-place."""
    from volcano_tpu import pipeline as pl

    store = _small(seed=29)
    store.pipeline = True
    sched = Scheduler(store)
    sched.run_once()
    assert store._inflight_solve is not None

    real_fetch = pl.InflightSolve.fetch
    calls = {"n": 0}

    def crash_once(self):
        if calls["n"] == 0:
            calls["n"] += 1
            raise RuntimeError("TPU worker process crashed mid-solve")
        return real_fetch(self)

    monkeypatch.setattr(pl.InflightSolve, "fetch", crash_once)
    sched.run_once()  # fetch crashes; budget halves; redispatch
    assert store._aff_budget_scale == 0.5
    sched.run_once()  # the redispatched solve lands
    store.flush_binds()
    assert all(p.node_name for p in store.pods.values())


def test_fetch_programming_error_propagates(monkeypatch):
    """A non-crash fetch error (local kind) is a programming error and
    must propagate, exactly as from a synchronous solve."""
    from volcano_tpu import pipeline as pl

    store = _small(seed=31)
    store.pipeline = True
    sched = Scheduler(store)
    sched.run_once()
    assert store._inflight_solve is not None

    def boom(self):
        raise ValueError("shape mismatch: solver returned garbage")

    monkeypatch.setattr(pl.InflightSolve, "fetch", boom)
    from volcano_tpu.fastpath import run_cycle_fast

    with pytest.raises(ValueError, match="shape mismatch"):
        run_cycle_fast(store, sched._load_conf())


def test_remote_garbage_replies_fail_cycle_after_cap(monkeypatch):
    """A solver child that keeps replying garbage never fails the
    send-side probe, so each cycle's fetch raises and used to be
    swallowed as a 'lost reply' forever — pods Pending, healthz green.
    Past REMOTE_FETCH_FAIL_CAP consecutive fetch failures the cycle
    must fail loudly (scheduler failure accounting takes over); one
    success resets the counter."""
    from volcano_tpu import pipeline as pl
    from volcano_tpu.fastpath import FastCycle, run_cycle_fast

    store = _small(seed=33)
    store.pipeline = True
    sched = Scheduler(store)
    conf = sched._load_conf()
    sched.run_once()
    assert store._inflight_solve is not None

    def garbage(self):
        raise ValueError("malformed snapshot frame")

    monkeypatch.setattr(pl.InflightSolve, "fetch", garbage)
    for _ in range(FastCycle.REMOTE_FETCH_FAIL_CAP - 1):
        # Present the parked handle as a remote dispatch; the failure
        # is swallowed and the cycle re-dispatches.
        store._inflight_solve.kind = "remote"
        run_cycle_fast(store, conf)
        assert store._inflight_solve is not None
    store._inflight_solve.kind = "remote"
    with pytest.raises(ValueError, match="malformed"):
        run_cycle_fast(store, conf)
    # Recovery: a successful fetch resets the consecutive counter (the
    # first cycle after the failure only re-dispatches; the fetch that
    # resets lands at the top of the one after).
    monkeypatch.undo()
    sched.run_once()
    sched.run_once()
    assert store._remote_fetch_fails == 0


# ------------------------------------------------------- stop / restart


def test_stop_mid_flight_abandons_dispatch_and_restart_places_all():
    store = _small(seed=11)
    store.pipeline = True
    sched = Scheduler(store)
    sched.run_once()
    assert store._inflight_solve is not None
    sched.stop()  # no loop thread: must still drain the dispatch
    assert store._inflight_solve is None

    # "Restarted" scheduler (fresh instance, same store): first cycles
    # re-place everything that was in flight.
    sched2 = Scheduler(store)
    sched2.run_once()
    sched2.run_once()
    store.flush_binds()
    assert all(p.node_name for p in store.pods.values())


def test_fallback_to_object_session_abandons_inflight(monkeypatch):
    """A cycle that leaves the fast path must not strand the in-flight
    handle where a later fast cycle would commit stale rows."""
    store = _small(seed=13)
    store.pipeline = True
    sched = Scheduler(store)
    sched.run_once()
    assert store._inflight_solve is not None

    monkeypatch.setenv("VOLCANO_TPU_FALLBACK", "always")
    from volcano_tpu import fastpath

    def explode(store_, conf):
        raise RuntimeError("fast path down")

    monkeypatch.setattr(fastpath, "run_cycle_fast", explode)
    import volcano_tpu.scheduler as sched_mod

    monkeypatch.setattr(sched_mod, "run_cycle_fast", explode,
                        raising=False)
    sched.run_once()  # falls back; must abandon the parked handle
    assert store._inflight_solve is None
    store.flush_binds()
    _assert_capacity_respected(store)


# ------------------------------------------------ device-resident planes


def test_devsnap_delta_upload_on_node_change():
    """A single-node mutation between cycles re-ships only the dirty
    rows (delta scatter), not the full plane set."""
    store = _small(seed=17, n_nodes=8, n_pods=16, gang_size=2)
    store.pipeline = True
    sched = Scheduler(store)
    sched.run_once()
    snap = store.device_snapshot
    assert snap.full_uploads >= 1
    full_before = snap.full_uploads

    # Node mutation: epoch bumps, one row dirty.
    store.add_node(Node(
        name="node-000000",
        allocatable={"cpu": "64", "memory": "256Gi", "pods": 256},
        labels={"freshly": "relabelled"},
    ))
    # New work so the next cycle actually solves.
    store.add_pod_group(PodGroup(name="late", min_member=1))
    store.add_pod(Pod(
        name="late-0",
        annotations={GROUP_NAME_ANNOTATION: "late"},
        containers=[{"cpu": "1", "memory": "1Gi"}],
    ))
    sched.run_once()
    sched.run_once()
    store.flush_binds()
    assert snap.delta_uploads >= 1
    assert snap.full_uploads == full_before
    assert all(p.node_name for p in store.pods.values())


def test_devsnap_steady_state_hits_without_node_changes():
    store = _small(seed=19)
    store.pipeline = True
    sched = Scheduler(store)
    sched.run_once()
    snap = store.device_snapshot
    # Re-pend half the pods (vectorized, via the mirror column) so the
    # next cycle solves again at an unchanged node epoch.
    m = store.mirror
    rows = np.flatnonzero(
        (m.p_status[:m.n_pods] == ST_BOUND) & m.p_alive[:m.n_pods]
    )
    sched.run_once()
    store.flush_binds()
    hits_before = snap.hits
    rows = np.flatnonzero(
        (m.p_status[:m.n_pods] == ST_BOUND) & m.p_alive[:m.n_pods]
    )
    m.p_status[rows] = ST_PENDING
    m.p_node[rows] = -1
    m.p_node_name[rows] = None
    m.mutation_seq += 1
    for p in store.pods.values():
        p.node_name = None
    store.mark_objects_stale()
    sched.run_once()
    assert snap.hits > hits_before
    assert snap.full_uploads == 1


# ------------------------------------------------------ remote pipeline


def test_remote_pipelined_two_process_parity():
    """--remote-solver pipelined sessions over two real OS processes:
    frame N+1 is sent while frame N's reply is outstanding, and the
    placements match the local synchronous loop (hack/run-e2e.sh runs
    this file as its pipelined-mode pass)."""
    from test_remote_solver import _spawn_solver

    from volcano_tpu.solver_service import RemoteSolver

    local = _small(seed=23)
    Scheduler(local).run_once()
    local.flush_binds()

    proc, port = _spawn_solver()
    try:
        remote = _small(seed=23)
        remote.pipeline = True
        client = RemoteSolver(f"127.0.0.1:{port}")
        remote.remote_solver = client
        sched = Scheduler(remote)
        sched.run_once()
        inflight = remote._inflight_solve
        assert inflight is not None and inflight.kind == "remote"
        sched.run_once()
        remote.flush_binds()
        assert _placements(local) == _placements(remote)
        assert client.ping()["solves"] >= 1  # the CHILD actually solved
        remote.close()
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except Exception:
            proc.kill()


# ----------------------------------------------------------- plumbing


def test_dispatch_slot_is_exclusive_remote_contract():
    """The remote protocol allows one outstanding solve: a second
    dispatch without a fetch must fail loudly, and abandon must clear
    the slot."""
    from volcano_tpu.solver_service import (
        PendingSolve,
        RemoteSolver,
        _WireCache,
    )

    client = RemoteSolver.__new__(RemoteSolver)
    import threading

    client._lock = threading.Lock()
    client._sock = None
    client._wire = _WireCache()
    client._shm = None
    client.wire_fallbacks = {}
    client._pending = PendingSolve(client)
    with pytest.raises(RuntimeError):
        client._roundtrip(b"x")
    client._pending.abandon()
    assert client._pending is None
