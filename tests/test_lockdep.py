"""Runtime lockdep (obs/lockdep.py, VOLCANO_TPU_LOCKDEP=1): the
annotation-derived enforcement must catch an injected unguarded
cross-thread write and an injected lock-order inversion, honor the
static suppression convention, stay fully inert behind its kill
switch, and run the pipelined sharded store anomaly-free.

Plus the writer-triad runtime regression the static family surfaced:
``EvictState.flush``'s failure-path reverts must stamp
``mutation_seq`` (the action loop stamped BEFORE the reverts).

Tier-1, CPU-only.
"""

from __future__ import annotations

import threading

import pytest

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    PodPhase,
    PriorityClass,
    Queue,
)
from volcano_tpu.cache import ClusterStore
from volcano_tpu.cache.interface import EvictFailure
from volcano_tpu.obs import lockdep
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.synth import synthetic_cluster

EVICT_CONF = """
actions: "enqueue, allocate, preempt, reclaim, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


def _lockdep_anomalies(store):
    with store.auditor._lock:
        return [a.to_dict() for a in store.auditor._ring
                if a.reason in ("lockdep-violation", "lock-order-cycle")]


# ------------------------------------------------------- kill switch
# Runs first in this file: asserts the probe never armed in THIS
# process before any enabling test below flips it on.


def test_kill_switch_leaves_store_unwrapped(monkeypatch):
    monkeypatch.delenv("VOLCANO_TPU_LOCKDEP", raising=False)
    lockdep.reset()
    store = ClusterStore()
    try:
        assert lockdep.stats()["active"] is False
        assert not isinstance(store._lock, lockdep._LockProxy)
        assert "_vclockdep_armed" not in store.__dict__
        if not lockdep._installed:
            assert not any(
                isinstance(v, lockdep._GuardedDescriptor)
                for v in vars(ClusterStore).values()
            )
        # Unguarded access reports nothing with the switch off.
        store._solve_seq = 7
        _ = store._solve_seq
        assert _lockdep_anomalies(store) == []
    finally:
        store.close()


# -------------------------------------------------------- fixtures


@pytest.fixture()
def armed_store(monkeypatch):
    monkeypatch.setenv("VOLCANO_TPU_LOCKDEP", "1")
    store = ClusterStore()
    assert lockdep.stats()["active"] is True
    assert isinstance(store._lock, lockdep._LockProxy)
    yield store
    store.close()
    lockdep.reset()


# ------------------------------------------------------- violations


def test_injected_unguarded_cross_thread_write_caught(armed_store):
    store = armed_store

    def rogue():
        store._solve_seq = 99  # guarded-by _lock, no lock held

    t = threading.Thread(target=rogue, name="rogue-writer")
    t.start()
    t.join()

    got = _lockdep_anomalies(store)
    assert len(got) == 1
    detail = got[0]["detail"]
    assert got[0]["reason"] == "lockdep-violation"
    assert detail["attribute"] == "_solve_seq"
    assert detail["lock"] == "_lock"
    assert detail["access"] == "write"
    assert detail["thread"] == "rogue-writer"
    assert any("test_lockdep" in fr for fr in detail["stack"])
    # The same broken site reports once, not per hit.
    t2 = threading.Thread(target=rogue, name="rogue-writer-2")
    t2.start()
    t2.join()
    assert len(_lockdep_anomalies(store)) == 1


def test_guarded_access_under_lock_is_clean(armed_store):
    store = armed_store
    with store._lock:
        store._solve_seq = 3
        assert store._solve_seq == 3
    assert lockdep.held_locks() == {}
    assert _lockdep_anomalies(store) == []


def test_injected_lock_order_inversion_caught(armed_store):
    store = armed_store

    def ab():
        with store._lock:
            with store._events_lock:
                pass

    def ba():
        with store._events_lock:
            with store._lock:
                pass

    for name, fn in (("t-ab", ab), ("t-ba", ba)):
        t = threading.Thread(target=fn, name=name)
        t.start()
        t.join()

    cycles = [a for a in _lockdep_anomalies(store)
              if a["reason"] == "lock-order-cycle"]
    assert len(cycles) == 1
    detail = cycles[0]["detail"]
    assert {detail["held"], detail["acquiring"]} == {
        "_lock", "_events_lock"}
    assert detail["cycle"][0] == detail["cycle"][-1]
    assert set(detail["cycle"]) == {"_lock", "_events_lock"}


def test_static_suppression_honored_at_runtime(armed_store):
    store = armed_store
    # vclint: disable=VCL101 -- reviewed unguarded probe (this test)
    _ = store.bind_backoff
    assert _lockdep_anomalies(store) == []
    # ... and the same read WITHOUT the annotation is a violation.
    _ = store.bind_backoff
    got = _lockdep_anomalies(store)
    assert len(got) == 1
    assert got[0]["detail"]["attribute"] == "bind_backoff"


# ------------------------------------------------- enforcement smoke


def test_pipelined_shard_store_runs_clean_under_enforcement(monkeypatch):
    """The pipelined, sharded control plane schedules a synthetic
    cluster end to end with enforcement on and reports nothing — the
    runtime analog of the committed tree linting clean."""
    from volcano_tpu.shard import ShardedScheduler

    monkeypatch.setenv("VOLCANO_TPU_LOCKDEP", "1")
    store = synthetic_cluster(n_nodes=4, n_pods=8, gang_size=2)
    try:
        store.pipeline = True
        sched = ShardedScheduler(store, shards=2)
        for _ in range(4):
            for s in sched.schedulers:
                s.run_once()
        store.flush_binds(timeout=30)
        assert _lockdep_anomalies(store) == []
        with store._lock:
            assert all(p.node_name for p in store.pods.values())
    finally:
        store.close()
        lockdep.reset()


# ------------------------------------- flush revert mutation_seq fix


class _AlwaysFailEvictor:
    """Evictor whose batch dispatch rejects every key."""

    def __init__(self):
        self.batches = 0

    def evict_keys(self, keys, reason="preempted"):
        self.batches += 1
        raise EvictFailure(list(keys))

    def evict(self, pod):
        raise EvictFailure([f"{pod.namespace}/{pod.name}"])


def _oversubscribed_store() -> ClusterStore:
    store = ClusterStore()
    store.add_priority_class(PriorityClass(name="low", value=100))
    store.add_priority_class(PriorityClass(name="high", value=10000))
    store.add_queue(Queue(name="victim", weight=1))
    store.add_queue(Queue(name="premium", weight=9))
    store.add_node(Node(name="n0",
                        allocatable={"cpu": "16", "memory": "32Gi"}))
    for k in range(2):
        pg = PodGroup(name=f"fill-{k}", min_member=1, queue="victim")
        store.add_pod_group(pg)
        store.add_pod(Pod(
            name=f"fill-{k}-0",
            annotations={GROUP_NAME_ANNOTATION: pg.name},
            containers=[{"cpu": "8", "memory": "16Gi"}],
            phase=PodPhase.Running, node_name="n0",
            priority_class="low", priority=100,
        ))
    store.add_pod_group(PodGroup(name="hi", min_member=1,
                                 queue="premium"))
    store.add_pod(Pod(
        name="hi-0",
        annotations={GROUP_NAME_ANNOTATION: "hi"},
        containers=[{"cpu": "12", "memory": "8Gi"}],
        priority_class="high", priority=10000,
    ))
    return store


def test_flush_failure_revert_stamps_mutation_seq(monkeypatch):
    """When evictions fail and flush() reverts the victims to Running,
    the revert itself must advance mutation_seq — the action loop
    stamped BEFORE flush ran, so without the fresh stamp the pipelined
    staleness guard and the cross-shard commit gate would validate an
    in-flight solve against pre-revert state."""
    from volcano_tpu.fastpath_evict import EvictState

    deltas = []
    orig_flush = EvictState.flush

    def spy(self):
        before = self.cyc.m.mutation_seq
        orig_flush(self)
        if self.evicted_rows:
            deltas.append(self.cyc.m.mutation_seq - before)

    monkeypatch.setattr(EvictState, "flush", spy)

    store = _oversubscribed_store()
    try:
        evictor = _AlwaysFailEvictor()
        store.evictor = evictor
        Scheduler(store, conf_str=EVICT_CONF).run_once()
        assert evictor.batches >= 1, "preempt never dispatched evictions"
        # All victims reverted (nothing left terminating) ...
        with store._lock:
            assert not any(p.deleting for p in store.pods.values())
        # ... and the revert batch stamped the mutation counter.
        assert deltas and all(d >= 1 for d in deltas), deltas
    finally:
        store.close()
