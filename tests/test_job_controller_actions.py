"""Job-controller action semantics.

The analog of the reference's action tests
(``pkg/controllers/job/job_controller_actions_test.go``: KillJob,
SyncJob, CreateJobIOIfNotExist, CreatePVC, CreatePodGroupIfNotExist,
DeleteJobPod) plus the applyPolicies table
(``job_controller_util.go:110-184``), driven directly against
``JobController`` with the store as the observable boundary.
"""

import pytest

from volcano_tpu.api import Node, PodGroupPhase, PodPhase
from volcano_tpu.cache import ClusterStore
from volcano_tpu.controllers import Job, JobController, TaskSpec
from volcano_tpu.controllers.apis import (
    Action,
    Event,
    LifecyclePolicy,
    Request,
    VolumeSpec,
)
from volcano_tpu.controllers.job_controller import apply_policies


def make_store():
    s = ClusterStore()
    s.add_node(Node(name="n0", allocatable={"cpu": "16", "memory": "32Gi",
                                            "pods": 110}))
    return s


def make_job(name="j1", replicas=2, min_available=2, volumes=None,
             ttl=None):
    return Job(
        name=name,
        min_available=min_available,
        tasks=[TaskSpec(name="worker", replicas=replicas,
                        containers=[{"cpu": "1", "memory": "1Gi"}])],
        volumes=volumes or [],
        ttl_seconds_after_finished=ttl,
    )


def open_gate(store, job):
    """Admit the job's PodGroup past Pending (the scheduler's enqueue
    gate) so sync creates pods."""
    pg = store.pod_groups[f"{job.namespace}/{job.name}"]
    pg.status.phase = PodGroupPhase.Inqueue.value
    store.update_pod_group(pg)


def job_pods(store, job):
    return [p for p in store.pods.values()
            if p.owner_job == job.key]


# ---------------------------------------------------------------- sync_job


def test_sync_creates_podgroup_with_min_resources():
    """CreatePodGroupIfNotExistFunc analog: initiate creates the gang
    PodGroup with MinResources aggregated from min_available tasks."""
    s = make_store()
    jc = JobController(s)
    job = make_job(replicas=3, min_available=2)
    jc.sync_job(job, None)
    pg = s.pod_groups["default/j1"]
    assert pg.min_member == 2
    assert pg.owner_job == "default/j1"
    # 2 (min_available) x 1 cpu.
    assert pg.min_resources["cpu"] == "2000m"


def test_sync_gates_pod_creation_on_podgroup_phase():
    """job_controller_actions.go:227-231: no pods until the PodGroup
    leaves Pending (the scheduler's enqueue admission)."""
    s = make_store()
    jc = JobController(s)
    job = make_job(replicas=2)
    jc.sync_job(job, None)
    assert job_pods(s, job) == []
    open_gate(s, job)
    jc.sync_job(job, None)
    assert len(job_pods(s, job)) == 2


def test_sync_scale_up_creates_missing_pods_only():
    s = make_store()
    jc = JobController(s)
    job = make_job(replicas=2)
    jc.sync_job(job, None)
    open_gate(s, job)
    jc.sync_job(job, None)
    first = {p.name for p in job_pods(s, job)}
    job.tasks[0].replicas = 4
    jc.sync_job(job, None)
    pods = job_pods(s, job)
    assert len(pods) == 4
    assert first <= {p.name for p in pods}  # originals survive


def test_sync_scale_down_deletes_excess_pods():
    s = make_store()
    jc = JobController(s)
    job = make_job(replicas=4)
    jc.sync_job(job, None)
    open_gate(s, job)
    jc.sync_job(job, None)
    job.tasks[0].replicas = 2
    jc.sync_job(job, None)
    alive = [p for p in job_pods(s, job) if not p.deleting]
    doomed = [p for p in job_pods(s, job) if p.deleting]
    assert len(alive) == 2
    assert len(doomed) == 2
    assert {p.name for p in alive} == {"j1-worker-0", "j1-worker-1"}


def test_sync_classifies_status_counters():
    s = make_store()
    jc = JobController(s)
    job = make_job(replicas=3)
    jc.sync_job(job, None)
    open_gate(s, job)
    jc.sync_job(job, None)
    pods = job_pods(s, job)
    import copy
    for pod, phase in zip(pods, (PodPhase.Running, PodPhase.Succeeded,
                                 PodPhase.Pending)):
        upd = copy.copy(pod)
        upd.phase = phase
        if phase != PodPhase.Pending:
            upd.node_name = "n0"
        s.update_pod(upd)
    jc.sync_job(job, None)
    assert job.status.running == 1
    assert job.status.succeeded == 1
    assert job.status.pending == 1


def test_sync_pod_names_are_deterministic_with_task_index():
    s = make_store()
    jc = JobController(s)
    job = make_job(replicas=2)
    jc.sync_job(job, None)
    open_gate(s, job)
    jc.sync_job(job, None)
    names = sorted(p.name for p in job_pods(s, job))
    assert names == ["j1-worker-0", "j1-worker-1"]
    by_name = {p.name: p for p in job_pods(s, job)}
    assert by_name["j1-worker-0"].annotations["volcano-tpu/task-index"] == "0"
    assert by_name["j1-worker-1"].annotations["volcano-tpu/task-index"] == "1"


# ------------------------------------------------------------- job IO/PVC


def test_create_job_io_creates_controller_owned_claim():
    """CreatePVCFunc analog: a volume with a claim SPEC creates the
    claim with the job as owner."""
    s = make_store()
    jc = JobController(s)
    job = make_job(volumes=[VolumeSpec(mount_path="/data",
                                       volume_claim={"storage": "10Gi"})])
    jc.sync_job(job, None)
    assert len(s.pvcs) == 1
    key, rec = next(iter(s.pvcs.items()))
    assert rec["owner_job"] == "default/j1"
    assert rec["spec"] == {"storage": "10Gi"}
    # The generated name is persisted on the spec for idempotency.
    assert job.volumes[0].volume_claim_name
    assert job.status.controlled_resources


def test_create_job_io_missing_named_claim_keeps_job_pending():
    """CreateJobIOIfNotExistFunc analog: a named claim that does not
    exist parks the job (no PodGroup, no pods) until it appears."""
    s = make_store()
    jc = JobController(s)
    job = make_job(volumes=[VolumeSpec(mount_path="/data",
                                       volume_claim_name="pre-existing")])
    jc.sync_job(job, None)
    assert "default/j1" not in s.pod_groups
    evs = s.events_for("Job/default/j1")
    assert any(e["reason"] == "PVCNotFound" for e in evs)
    # Claim appears -> next sync proceeds.
    s.put_pvc("default", "pre-existing", {"storage": "1Gi"})
    jc.sync_job(job, None)
    assert "default/j1" in s.pod_groups


def test_create_job_io_idempotent_across_syncs():
    s = make_store()
    jc = JobController(s)
    job = make_job(volumes=[VolumeSpec(mount_path="/data",
                                       volume_claim={"storage": "10Gi"})])
    jc.sync_job(job, None)
    jc.sync_job(job, None)
    assert len(s.pvcs) == 1  # no duplicate claim per sync


def test_pods_mount_job_volumes():
    s = make_store()
    jc = JobController(s)
    job = make_job(replicas=1,
                   volumes=[VolumeSpec(mount_path="/data",
                                       volume_claim={"storage": "1Gi"})])
    jc.sync_job(job, None)
    open_gate(s, job)
    jc.sync_job(job, None)
    (pod,) = job_pods(s, job)
    claim = job.volumes[0].volume_claim_name
    assert (claim, "/data") in pod.volumes


# ---------------------------------------------------------------- kill_job


def test_kill_deletes_pods_and_podgroup_and_bumps_version():
    """KillJobFunc analog: pods deleted, PodGroup removed, job version
    incremented (stale-generation pod events then degrade to sync)."""
    s = make_store()
    jc = JobController(s)
    job = make_job(replicas=3)
    jc.sync_job(job, None)
    open_gate(s, job)
    jc.sync_job(job, None)
    v0 = job.status.version
    jc.kill_job(job, retain_phases=set(), update_status=None)
    assert all(p.deleting for p in job_pods(s, job))
    assert "default/j1" not in s.pod_groups
    assert job.status.version == v0 + 1


def test_kill_retains_requested_phases():
    """DeleteJobPod analog with retain: Succeeded pods survive a kill
    that retains them (restart semantics keep completed work)."""
    s = make_store()
    jc = JobController(s)
    job = make_job(replicas=2)
    jc.sync_job(job, None)
    open_gate(s, job)
    jc.sync_job(job, None)
    pods = job_pods(s, job)
    import copy
    done = copy.copy(pods[0])
    done.phase = PodPhase.Succeeded
    done.node_name = "n0"
    s.update_pod(done)
    jc.kill_job(job, retain_phases={PodPhase.Succeeded}, update_status=None)
    survivors = [p for p in job_pods(s, job) if not p.deleting]
    assert len(survivors) == 1
    assert survivors[0].phase == PodPhase.Succeeded


def test_cleanup_job_reaps_owned_claims():
    """Owner-reference cleanup: controller-created claims die with the
    job; pre-existing user claims survive."""
    s = make_store()
    s.put_pvc("default", "user-claim", {"storage": "1Gi"})
    jc = JobController(s)
    job = make_job(volumes=[
        VolumeSpec(mount_path="/data", volume_claim={"storage": "10Gi"}),
        VolumeSpec(mount_path="/user", volume_claim_name="user-claim"),
    ])
    jc.sync_job(job, None)
    assert len(s.pvcs) == 2
    jc._cleanup_job(job)
    assert list(s.pvcs) == ["default/user-claim"]


# ------------------------------------------------------------ applyPolicies


def _policy_job(job_policies=None, task_policies=None):
    return Job(
        name="p1",
        min_available=1,
        tasks=[TaskSpec(name="worker", replicas=1,
                        containers=[{"cpu": "1"}],
                        policies=task_policies or [])],
        policies=job_policies or [],
    )


@pytest.mark.parametrize("req,job_policies,task_policies,expected", [
    # Explicit action on the request wins outright.
    (Request(namespace="default", job_name="p1",
             action=Action.RestartJob.value),
     [], [], Action.RestartJob.value),
    # OutOfSync always degrades to SyncJob.
    (Request(namespace="default", job_name="p1",
             event=Event.OutOfSync.value),
     [LifecyclePolicy(event=Event.Any.value,
                      action=Action.RestartJob.value)],
     [], Action.SyncJob.value),
    # Job-level policy matches the event.
    (Request(namespace="default", job_name="p1",
             event=Event.PodFailed.value),
     [LifecyclePolicy(event=Event.PodFailed.value,
                      action=Action.RestartJob.value)],
     [], Action.RestartJob.value),
    # Any-event policy matches every event.
    (Request(namespace="default", job_name="p1",
             event=Event.PodEvicted.value),
     [LifecyclePolicy(event=Event.Any.value,
                      action=Action.RestartJob.value)],
     [], Action.RestartJob.value),
    # Task-level policy wins over job-level for its task.
    (Request(namespace="default", job_name="p1", task_name="worker",
             event=Event.PodFailed.value),
     [LifecyclePolicy(event=Event.PodFailed.value,
                      action=Action.RestartJob.value)],
     [LifecyclePolicy(event=Event.PodFailed.value,
                      action=Action.AbortJob.value)],
     Action.AbortJob.value),
    # Exit-code policy match.
    (Request(namespace="default", job_name="p1",
             event=Event.PodFailed.value, exit_code=137),
     [LifecyclePolicy(exit_code=137,
                      action=Action.TerminateJob.value)],
     [], Action.TerminateJob.value),
    # No policy matches -> SyncJob default.
    (Request(namespace="default", job_name="p1",
             event=Event.PodEvicted.value),
     [LifecyclePolicy(event=Event.PodFailed.value,
                      action=Action.RestartJob.value)],
     [], Action.SyncJob.value),
])
def test_apply_policies_table(req, job_policies, task_policies, expected):
    job = _policy_job(job_policies, task_policies)
    assert apply_policies(job, req) == expected


def test_apply_policies_stale_version_degrades_to_sync():
    job = _policy_job([LifecyclePolicy(event=Event.PodFailed.value,
                                       action=Action.RestartJob.value)])
    job.status.version = 5
    req = Request(namespace="default", job_name="p1",
                  event=Event.PodFailed.value, job_version=3)
    assert apply_policies(job, req) == Action.SyncJob.value
