"""Metrics batch-update parity: set_many/inc_many must land
identically to per-call set/inc (the gang-close fast path uses the
batch forms with prebuilt label keys)."""


def test_metrics_batch_updates_match_singles():
    """set_many/inc_many must land identically to per-call set/inc."""
    from volcano_tpu.metrics.metrics import Metrics

    a, b = Metrics(), Metrics()
    names = [f"job-{i}" for i in range(40)]
    for i, n in enumerate(names):
        a.unschedule_task_count.set(i, job_name=n)
        a.job_retry_counts.inc(job_name=n)
        a.job_retry_counts.inc(job_name=n)
    b.unschedule_task_count.set_many(
        ((("job_name", n),), i) for i, n in enumerate(names)
    )
    keys = [(("job_name", n),) for n in names]
    b.job_retry_counts.inc_many(keys)
    b.job_retry_counts.inc_many(keys)
    assert a.unschedule_task_count.data == b.unschedule_task_count.data
    assert a.job_retry_counts.data == b.job_retry_counts.data
