"""Solver replica pool (ISSUE 15, volcano_tpu/solver_pool.py).

Pins the pool's acceptance contracts: multi-process parity vs the
single connection, hedged-dispatch first-wins determinism with the
slow reply drained, failover-within-one-cycle with zero lost pods,
what-if-offload overlap with unchanged commit semantics, pool-of-1
bitwise equality to today's path, and the kill switch.
"""

import os
import threading
import time

import numpy as np
import pytest

from volcano_tpu.api import TaskStatus
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.solver_pool import SolverPool, make_solver_client
from volcano_tpu.solver_service import RemoteSolver, SolverServer
from volcano_tpu.synth import synthetic_cluster

from test_remote_solver import _local_loop, _spawn_solver

ST_BOUND = int(TaskStatus.Bound)


@pytest.fixture()
def servers():
    """Two in-process solver servers (each connection gets its own
    thread + mirror + devincr context, exactly like separate
    processes for the wire's purposes)."""
    out = []
    for _ in range(2):
        s = SolverServer(port=0)
        threading.Thread(target=s.serve_forever, daemon=True).start()
        out.append(s)
    yield out
    for s in out:
        try:
            s.shutdown()
        except OSError:
            pass


def _pool_loop(pool, *, cycles=10, seed=31, churn=True,
               feed_nodes=(0, 1)):
    """Pipelined pool twin of test_remote_solver._wire_loop (same
    seeds, same churn sequence)."""
    import random

    from test_devincr import (
        _churn,
        _mirror_state,
        _partial_feed,
        _reset_uid_counters,
    )

    _reset_uid_counters()
    store = synthetic_cluster(n_nodes=16, n_pods=48, gang_size=4,
                              seed=seed)
    store.pipeline = True
    store.remote_solver = pool
    store.cycle_feed = _partial_feed(list(feed_nodes))
    sched = Scheduler(store)
    rng = random.Random(7)
    states = []
    for step in range(cycles):
        sched.run_once()
        states.append(_mirror_state(store))
        if churn and step % 2 == 1:
            _churn(store, rng, step)
    store.flush_binds()
    binds = dict(store.binder.binds)
    store.close()
    return binds, states


def test_pool_two_process_churn_parity(monkeypatch):
    """A pool of two REAL solver child processes stays bind-for-bind
    and per-cycle-mirror-state equal to the in-process loop across a
    randomized-churn feed — any replica can serve any solve, and each
    replica's deltas re-engage after its first full frame."""
    monkeypatch.setenv("VOLCANO_TPU_WIRE", "1")
    procs = []
    try:
        addrs = []
        for _ in range(2):
            proc, port = _spawn_solver()
            procs.append(proc)
            addrs.append(f"127.0.0.1:{port}")
        pool = SolverPool(addrs)
        binds_p, states_p = _pool_loop(pool, cycles=10, churn=True)
        frames = pool.per_replica_frames()
        pool.close()
        binds_l, states_l = _local_loop(cycles=10, churn=True)
        assert binds_p and binds_p == binds_l
        assert states_p == states_l
        # Both replicas served solves; whichever served more than one
        # frame re-engaged deltas after its first (always-full) frame.
        assert all(f["full"] >= 1 for f in frames), frames
        assert any(f["delta"] >= 1 for f in frames), frames
    finally:
        for proc in procs:
            proc.terminate()
            proc.wait(timeout=10)


def test_pool_of_one_bitwise_equal_to_single_client(servers,
                                                    monkeypatch):
    """Pool of 1 (the VOLCANO_TPU_SOLVER_POOL=1 default semantics) is
    bind-for-bind, mirror-state, frame-kind AND wire-byte identical to
    the plain single-connection RemoteSolver path."""
    monkeypatch.setenv("VOLCANO_TPU_WIRE", "1")
    addr = f"127.0.0.1:{servers[0].port}"
    pool = SolverPool([addr], size=1)
    binds_p, states_p = _pool_loop(pool, cycles=8, churn=True)
    pool_frames = dict(pool.frame_counts)
    pool_bytes = dict(pool.frame_bytes)
    pool.close()

    from test_devincr import _partial_feed, _reset_uid_counters
    import random

    from test_devincr import _churn, _mirror_state

    _reset_uid_counters()
    client = RemoteSolver(addr)
    store = synthetic_cluster(n_nodes=16, n_pods=48, gang_size=4,
                              seed=31)
    store.pipeline = True
    store.remote_solver = client
    store.cycle_feed = _partial_feed([0, 1])
    sched = Scheduler(store)
    rng = random.Random(7)
    states_s = []
    for step in range(8):
        sched.run_once()
        states_s.append(_mirror_state(store))
        if step % 2 == 1:
            _churn(store, rng, step)
    store.flush_binds()
    binds_s = dict(store.binder.binds)
    single_frames = dict(client.frame_counts)
    single_bytes = dict(client.frame_bytes)
    store.close()
    client.close()

    assert binds_p and binds_p == binds_s
    assert states_p == states_s
    assert pool_frames == single_frames
    # Wire-byte identity: the pool of one adds no machinery to the
    # frames themselves.
    assert pool_bytes == single_bytes


def test_hedged_dispatch_first_wins_and_drains(servers, monkeypatch):
    """A straggling primary past its rolling-p99 deadline re-dispatches
    the identical frame to the second replica; the first valid reply
    commits, the loser's reply is drained (its connection and mirror
    stay coherent — deltas continue afterwards), and the binds are
    deterministic (equal to an unhedged run)."""
    monkeypatch.setenv("VOLCANO_TPU_WIRE", "1")
    monkeypatch.setenv("VOLCANO_TPU_POOL_HEDGE_P99_MULT", "2.0")
    monkeypatch.setenv("VOLCANO_TPU_POOL_HEDGE_MIN_MS", "20")
    for s in servers:
        s.solve_delay_fn = lambda i: 0.25 if i % 4 == 0 else 0.0
    pool = SolverPool([f"127.0.0.1:{s.port}" for s in servers])
    binds_h, states_h = _pool_loop(pool, cycles=12, churn=False)
    snap = pool.health_snapshot()
    assert snap["hedge_dispatches"] >= 1, snap
    assert snap["hedge_wins"] >= 1, snap
    # The loser's reply is DRAINED (received + discarded), never
    # abandoned: no connection was torn down for a hedge (abandon /
    # reconnect would void the loser's wire cache), and a blocking
    # drain of whatever is still parked leaves every replica clean.
    assert pool.wire_fallbacks.get("abandon", 0) == 0
    for r in pool.replicas:
        pool._drain(r, block=True)
    snap = pool.health_snapshot()
    assert all(not r["draining"] for r in snap["replicas"]), snap
    pool.close()

    # Determinism: the same loop with hedging disabled lands the
    # identical binds and mirror states (first-wins is safe because
    # replies are deterministic for identical frames).
    monkeypatch.setenv("VOLCANO_TPU_POOL_HEDGE_P99_MULT", "0")
    for s in servers:
        s.solve_delay_fn = None
    pool2 = SolverPool([f"127.0.0.1:{s.port}" for s in servers])
    binds_n, states_n = _pool_loop(pool2, cycles=12, churn=False)
    assert pool2.health_snapshot()["hedge_dispatches"] == 0
    pool2.close()
    assert binds_h and binds_h == binds_n
    assert states_h == states_n


def test_failover_within_one_cycle_zero_lost_pods(servers,
                                                  monkeypatch):
    """Killing the replica holding the in-flight solve costs exactly
    one cycle's lost-reply re-place: the fetch routes through the
    existing lost-reply machinery, the NEXT dispatch fails over to the
    healthy replica (full frame by construction), and no pod is lost."""
    monkeypatch.setenv("VOLCANO_TPU_WIRE", "1")
    from test_devincr import _partial_feed, _reset_uid_counters

    _reset_uid_counters()
    pool = SolverPool([f"127.0.0.1:{s.port}" for s in servers])
    store = synthetic_cluster(n_nodes=16, n_pods=48, gang_size=4,
                              seed=37)
    store.pipeline = True
    store.remote_solver = pool
    store.cycle_feed = _partial_feed([0, 1])
    sched = Scheduler(store)
    for _ in range(5):
        sched.run_once()
    # Kill the replica with the in-flight solve: shut its server down
    # AND sever the live connection (a real child death does both).
    prim = pool.health_snapshot()["primary"]
    servers[prim].shutdown()
    victim = pool.replicas[prim].client
    with victim._lock:
        victim._close_locked("kill")
    other = 1 - prim
    # The kill cycle: lost reply counted, rows re-place, NO stall.
    sched.run_once()
    rec = store.flight.recent()[-1]
    assert rec.drop_reasons.get("lost-reply", 0) >= 1, rec.drop_reasons
    assert rec.error is None
    # Failover landed within the same cycle's dispatch: the healthy
    # replica took the frame (its first frame is full).
    snap = pool.health_snapshot()
    assert snap["failovers"] >= 1, snap
    assert snap["primary"] == other, snap
    assert pool.replicas[other].client.frame_counts["full"] >= 1
    # Drain: every pod lands Bound — zero lost pods.
    for _ in range(3):
        sched.run_once()
    store.cycle_feed = None
    for _ in range(3):
        sched.run_once()
    store.flush_binds()
    m = store.mirror
    not_bound = [
        m.p_uid[r] for r in range(m.n_pods)
        if m.p_uid[r] is not None and m.p_alive[r]
        and int(m.p_status[r]) != ST_BOUND
    ]
    assert not_bound == [], f"pods lost to the kill: {not_bound}"
    assert store.auditor.total_anomalies() == 0
    store.close()
    pool.close()


PREEMPT_CONF = """
actions: "enqueue, allocate, preempt, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


def test_whatif_offload_overlap(servers, monkeypatch):
    """With a pool, the device-native preempt lane turns ON for remote
    stores: the plan-proving solve offloads to an idle NON-primary
    replica (overlapping the allocate lane's in-flight solve instead of
    contending for it) and the commit semantics are unchanged — the
    starved gang binds, victims restore through the ledger, zero lost
    pods."""
    monkeypatch.setenv("VOLCANO_TPU_EVICT_DEVICE", "1")
    from volcano_tpu.cache import ClusterStore
    from volcano_tpu.cache.interface import FakeBinder, FakeEvictor
    from volcano_tpu.metrics import metrics
    from volcano_tpu.sim import ClusterSimulator

    def _whatif_dispatches():
        return sum(
            v for k, v in metrics.solver_pool_dispatch.data.items()
            if dict(k).get("kind") == "whatif"
        )

    before = _whatif_dispatches()
    pool = SolverPool([f"127.0.0.1:{s.port}" for s in servers])
    store = ClusterStore(evictor=FakeEvictor(), binder=FakeBinder())
    store.pipeline = True
    store.remote_solver = pool
    ClusterSimulator.priority_tier_workload(store, workers=4,
                                            serving_tasks=2)
    # Lock held for the read: the lockdep leg (VOLCANO_TPU_LOCKDEP=1)
    # holds test code to the same guarded-attribute contract.
    with store._lock:
        n_logical = len(store.pods)
    sched = Scheduler(store, conf_str=PREEMPT_CONF)
    sim = ClusterSimulator(store, grace_steps=2)
    bound = 0
    for _ in range(16):
        sched.run_once()
        sim.step()
        with store._lock:
            bound = sum(1 for p in store.pods.values()
                        if p.name.startswith("serving-") and p.node_name)
        if bound >= 2:
            break
    assert bound >= 2, "serving gang did not bind"
    # The plan solve actually offloaded (kind=whatif dispatches), and
    # it went to a replica other than the allocate primary.
    assert _whatif_dispatches() > before
    ledger = store.migrations
    assert ledger is not None and ledger.committed_plans >= 1
    # Commit semantics unchanged: zero lost pods (every victim
    # restored), budgets intact.
    with store._lock:
        assert len(store.pods) == n_logical
    assert store.auditor.total_anomalies() == 0
    store.close()
    pool.close()


def test_whatif_stays_off_without_offload_capacity(servers,
                                                   monkeypatch):
    """A single-connection remote store (no pool, or a pool of one)
    keeps the engine off exactly as before — the plan solve would
    contend for the one connection."""
    monkeypatch.setenv("VOLCANO_TPU_EVICT_DEVICE", "1")
    from volcano_tpu import whatif
    from volcano_tpu.cache import ClusterStore

    store = ClusterStore()
    store.remote_solver = RemoteSolver(
        f"127.0.0.1:{servers[0].port}")
    assert not whatif.evict_device_on(store)
    store.remote_solver = SolverPool(
        [f"127.0.0.1:{servers[0].port}"], size=1)
    assert not whatif.evict_device_on(store)
    store.remote_solver = None
    assert whatif.evict_device_on(store)
    store.close()


def test_kill_switch_builds_plain_client(monkeypatch):
    """VOLCANO_TPU_SOLVER_POOL default (1) builds a plain RemoteSolver
    — no pool object at all, exactly today's path; >= 2 (or multiple
    addresses) builds the pool."""
    monkeypatch.delenv("VOLCANO_TPU_SOLVER_POOL", raising=False)
    c = make_solver_client("127.0.0.1:1")
    assert isinstance(c, RemoteSolver)
    monkeypatch.setenv("VOLCANO_TPU_SOLVER_POOL", "3")
    c = make_solver_client("127.0.0.1:1")
    assert isinstance(c, SolverPool) and c.size == 3
    monkeypatch.delenv("VOLCANO_TPU_SOLVER_POOL")
    c = make_solver_client("127.0.0.1:1,127.0.0.1:2")
    assert isinstance(c, SolverPool) and c.size == 2
    addrs = [(r.client.host, r.client.port) for r in c.replicas]
    assert addrs == [("127.0.0.1", 1), ("127.0.0.1", 2)]
