"""Eviction-path oracle + fast-vs-object victim-set parity fuzz.

VERDICT r2 #3: randomized oversubscribed snapshots must produce
IDENTICAL victim sets from the vectorized eviction path
(``fastpath_evict.py``) and the Go-shaped object session
(``actions/preempt.py`` / ``actions/reclaim.py``, forced via
``VOLCANO_TPU_FASTPATH=0``) — two structurally independent
implementations of preempt.go:41-262 / reclaim.go:40-189.  Plus the
pure-NumPy victim-selection oracles (``oracle.oracle_victims``,
``oracle_gang_protection``) on constructed scenarios, and the
statement-rollback exactness property (statement.go:324-367).
"""

import os

import numpy as np
import pytest

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    PodPhase,
    PriorityClass,
    Queue,
)
from volcano_tpu.cache import ClusterStore
from volcano_tpu.oracle import (
    np_less_equal,
    oracle_gang_protection,
    oracle_victims,
)
from volcano_tpu.scheduler import Scheduler

# Fuzz breadth is env-scalable (the durable CI default is 8 seeds per
# family; `hack/run-fuzz-nightly.sh` runs the same families at 150).
FUZZ_SEEDS = int(os.environ.get("VOLCANO_TPU_FUZZ_SEEDS", "8"))
FUZZ_SEEDS_SMALL = max(4, FUZZ_SEEDS // 2)

EVICT_CONF = """
actions: "enqueue, allocate, preempt, reclaim, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


def oversubscribed_store(seed: int) -> ClusterStore:
    """Randomized but seed-deterministic oversubscribed cluster:
    running filler gangs (mixed sizes/min_member, some critical pods)
    in a weight-1 queue, pending high-priority gangs in a weight-9
    queue; occasionally a reclaimable=False queue in the mix."""
    rng = np.random.default_rng(seed)
    store = ClusterStore()
    store.add_priority_class(PriorityClass(name="low", value=100))
    store.add_priority_class(PriorityClass(name="mid", value=1000))
    store.add_priority_class(PriorityClass(name="high", value=10000))
    store.add_queue(Queue(name="victim", weight=1,
                          reclaimable=bool(rng.random() < 0.8)))
    store.add_queue(Queue(name="premium", weight=9))
    # ~half the seeds run TWO pending queues: the cross-queue
    # round-robin (queue heap by live share/create/uid) is then part of
    # the fast-vs-object identity check — the surface the multi-queue
    # C drive owns.
    second_queue = bool(rng.random() < 0.5)
    if second_queue:
        store.add_queue(Queue(name="premium2", weight=5))
    n_nodes = int(rng.integers(3, 9))
    node_cpu = int(rng.integers(16, 33))
    for i in range(n_nodes):
        store.add_node(Node(
            name=f"node-{i:03d}",
            allocatable={"cpu": str(node_cpu),
                         "memory": f"{node_cpu * 4}Gi", "pods": 64},
            # Topology labels ride the eviction/placement machinery
            # (zone-keyed domains exist even when no pod selects them).
            topology={"topology.kubernetes.io/zone": f"zone-{i % 3}"},
        ))
    # Fill nodes with running gangs from the victim queue.
    g = 0
    for i in range(n_nodes):
        budget = node_cpu
        while budget >= 4:
            size = int(rng.integers(1, 4))
            min_member = int(rng.integers(1, size + 1))
            cpu = int(rng.choice([4, 8]))
            if cpu > budget:
                cpu = 4
            if cpu * size > budget:
                size = budget // cpu
                min_member = min(min_member, size)
            prio_name, prio = ("mid", 1000) if rng.random() < 0.3 else (
                "low", 100)
            critical = rng.random() < 0.1
            pg = PodGroup(name=f"fill-{g:04d}", min_member=min_member,
                          queue="victim")
            store.add_pod_group(pg)
            for k in range(size):
                # ~10% of victims hold a claim: eviction of volume-
                # carrying pods must not disturb the claim registry or
                # diverge the victim sets.
                volumes = []
                if rng.random() < 0.1:
                    claim = f"claim-fill-{g:04d}-{k}"
                    store.put_pvc("default", claim, {"storage": "1Gi"})
                    volumes = [(claim, "/data")]
                store.add_pod(Pod(
                    name=f"fill-{g:04d}-{k}",
                    annotations={GROUP_NAME_ANNOTATION: pg.name},
                    containers=[{"cpu": str(cpu),
                                 "memory": f"{cpu * 2}Gi"}],
                    phase=PodPhase.Running,
                    node_name=f"node-{i:03d}",
                    volumes=volumes,
                    priority_class=(
                        "system-node-critical" if critical else prio_name
                    ),
                    priority=prio,
                ))
                budget -= cpu
                if budget < 0:
                    break
            g += 1
    # Pending high-priority gangs that only fit by evicting.
    for j in range(int(rng.integers(2, 6))):
        size = int(rng.integers(1, 4))
        qname = (
            "premium2" if second_queue and rng.random() < 0.5
            else "premium"
        )
        pg = PodGroup(name=f"hi-{j:03d}", min_member=size,
                      queue=qname)
        store.add_pod_group(pg)
        for k in range(size):
            # ~20% of preemptors carry a claim; any that allocate in the
            # same cycle exercise the commit-path volume gate.
            volumes = []
            if rng.random() < 0.2:
                claim = f"claim-hi-{j:03d}-{k}"
                store.put_pvc("default", claim, {"storage": "1Gi"})
                volumes = [(claim, "/data")]
            store.add_pod(Pod(
                name=f"hi-{j:03d}-{k}",
                annotations={GROUP_NAME_ANNOTATION: pg.name},
                containers=[{"cpu": str(int(rng.choice([8, 12]))),
                             "memory": "8Gi"}],
                volumes=volumes,
                priority_class="high",
                priority=10000,
            ))
    return store


def run_cycle(store: ClusterStore, fastpath: bool, monkeypatch) -> None:
    monkeypatch.setenv("VOLCANO_TPU_FASTPATH",
                       "1" if fastpath else "0")
    Scheduler(store, conf_str=EVICT_CONF).run_once()


def evicted_keys(store: ClusterStore) -> set:
    return set(getattr(store.evictor, "evicts", []))


@pytest.mark.parametrize("seed", range(FUZZ_SEEDS))
def test_fast_vs_object_victim_sets_identical(seed, monkeypatch):
    fast_store = oversubscribed_store(seed)
    obj_store = oversubscribed_store(seed)
    run_cycle(fast_store, True, monkeypatch)
    run_cycle(obj_store, False, monkeypatch)
    assert evicted_keys(fast_store) == evicted_keys(obj_store), (
        f"seed {seed}: victim sets diverge\n"
        f"fast-only: {evicted_keys(fast_store) - evicted_keys(obj_store)}\n"
        f"object-only: {evicted_keys(obj_store) - evicted_keys(fast_store)}"
    )


@pytest.mark.parametrize("seed", range(FUZZ_SEEDS))
def test_gang_protection_property(seed, monkeypatch):
    """gang.go:74-98: an eviction never takes a running job below its
    MinAvailable (unless MinAvailable == 1)."""
    store = oversubscribed_store(seed)
    before = {}
    for pg in store.pod_groups.values():
        running = [p for p in store.pods.values()
                   if p.annotations.get(GROUP_NAME_ANNOTATION) == pg.name
                   and p.phase == PodPhase.Running]
        before[pg.name] = len(running)
    run_cycle(store, True, monkeypatch)
    evicted_by_group = {}
    for key in evicted_keys(store):
        ns, name = key.split("/", 1)
        pod = next(p for p in store.pods.values()
                   if p.namespace == ns and p.name == name)
        grp = pod.annotations[GROUP_NAME_ANNOTATION]
        evicted_by_group[grp] = evicted_by_group.get(grp, 0) + 1
    for grp, n_evicted in evicted_by_group.items():
        pg = store.pod_groups[f"default/{grp}"]
        if pg.min_member == 1:
            continue
        assert before[grp] - n_evicted >= pg.min_member, (
            f"seed {seed}: gang {grp} (min {pg.min_member}) dropped from "
            f"{before[grp]} to {before[grp] - n_evicted}"
        )


@pytest.mark.parametrize("seed", range(FUZZ_SEEDS))
def test_conformance_property(seed, monkeypatch):
    """conformance.go:44-66: critical pods are never victims."""
    store = oversubscribed_store(seed)
    critical = {
        f"{p.namespace}/{p.name}" for p in store.pods.values()
        if p.priority_class in ("system-cluster-critical",
                                "system-node-critical")
    }
    run_cycle(store, True, monkeypatch)
    assert not (evicted_keys(store) & critical)


@pytest.mark.parametrize("fastpath", [True, False])
def test_statement_rollback_exactness(fastpath, monkeypatch):
    """statement.go:324-367: a preemptor that can never reach Pipelined
    commits NOTHING — no evictions dispatch and node accounting is
    byte-identical to the pre-cycle state."""
    store = ClusterStore()
    store.add_priority_class(PriorityClass(name="low", value=100))
    store.add_priority_class(PriorityClass(name="high", value=10000))
    store.add_queue(Queue(name="victim", weight=1))
    store.add_queue(Queue(name="premium", weight=9))
    store.add_node(Node(name="n0", allocatable={"cpu": "16",
                                                "memory": "32Gi"}))
    pg = PodGroup(name="fill", min_member=1, queue="victim")
    store.add_pod_group(pg)
    for k in range(2):
        store.add_pod(Pod(
            name=f"fill-{k}",
            annotations={GROUP_NAME_ANNOTATION: "fill"},
            containers=[{"cpu": "8", "memory": "16Gi"}],
            phase=PodPhase.Running, node_name="n0",
            priority_class="low", priority=100,
        ))
    # Preemptor demands more than the node even empty (32 cpu > 16):
    # evicting every victim still can't pipeline it.
    store.add_pod_group(PodGroup(name="huge", min_member=1,
                                 queue="premium"))
    store.add_pod(Pod(
        name="huge-0",
        annotations={GROUP_NAME_ANNOTATION: "huge"},
        containers=[{"cpu": "32", "memory": "64Gi"}],
        priority_class="high", priority=10000,
    ))
    used_before = store.nodes["n0"].used.clone()
    run_cycle(store, fastpath, monkeypatch)
    assert not evicted_keys(store)
    assert not any(p.deleting for p in store.pods.values())
    node = store.nodes["n0"]
    assert abs(node.used.milli_cpu - used_before.milli_cpu) < 1e-6
    assert abs(node.used.memory - used_before.memory) < 1e-6
    running = [p for p in store.pods.values()
               if p.phase == PodPhase.Running and not p.deleting]
    assert len(running) == 2


# ---------------- pure-NumPy victim-selection oracle units ----------------

EPS = np.asarray([10.0, 10 * 2**20], np.float32)
NOSCAL = np.zeros(2, bool)


def test_oracle_victims_prefix_semantics():
    # Milli-cpu units (eps = 10 mCPU).  Node future idle 2 cpu;
    # preemptor wants 10 cpu; victims 4 cpu each, order ascending =
    # evicted first.
    victims = np.asarray([[4000.0, 0], [4000.0, 0], [4000.0, 0]],
                         np.float32)
    sel = oracle_victims([10000.0, 0.0], [2000.0, 0.0], victims,
                         victims_order=[2, 0, 1], eps=EPS,
                         scalar_slot=NOSCAL)
    # Evicts order-0 (idx 1) then order-1 (idx 2): 2+4+4 >= 10.
    assert sel.evicted.tolist() == [1, 2]
    assert sel.satisfied
    assert np_less_equal([10000.0, 0.0], sel.future_idle, EPS, NOSCAL)


def test_oracle_victims_insufficient():
    sel = oracle_victims([100000.0, 0.0], [2000.0, 0.0],
                         [[4000.0, 0.0]], [0], EPS, NOSCAL)
    assert sel.evicted.tolist() == [0] and not sel.satisfied


def test_oracle_victims_no_evictions_needed():
    sel = oracle_victims([1000.0, 0.0], [2000.0, 0.0],
                         [[4000.0, 0.0]], [0], EPS, NOSCAL)
    assert sel.evicted.tolist() == [] and sel.satisfied


def test_oracle_gang_protection_walk():
    # Jobs: 0 (min 2, ready 3), 1 (min 1, ready 1), 2 (min 3, ready 3).
    min_av = [2, 1, 3]
    ready = [3, 1, 3]
    victims_of = [0, 0, 1, 2, 0]
    allowed = oracle_gang_protection(min_av, ready, victims_of)
    # Job 0: first victim ok (3->2 >= 2), second not (2->1 < 2);
    # job 1: min 1 always allowed; job 2: 3->2 < 3 disallowed.
    assert allowed.tolist() == [True, False, True, False, False]


# -------------- enqueue / backfill oracle parity (all five actions) --------


def Gi(n):
    return float(n) * 2**30


def test_oracle_enqueue_parity_with_fast_cycle(monkeypatch):
    """enqueue.go budget walk: the fast cycle's Inqueue decisions match
    oracle_enqueue on the same dense encoding (incl. a MinResources-nil
    group and a rejected tail group)."""
    store = ClusterStore()
    store.add_node(Node(name="n0", allocatable={"cpu": "10",
                                                "memory": "10Gi"}))
    specs = [("g1", {"cpu": "4", "memory": "1Gi"}),
             ("g2", None),
             ("g3", {"cpu": "6", "memory": "1Gi"}),
             ("g4", {"cpu": "4", "memory": "1Gi"})]
    for name, minres in specs:
        store.add_pod_group(PodGroup(name=name, min_member=1,
                                     min_resources=minres))
    monkeypatch.setenv("VOLCANO_TPU_FASTPATH", "1")
    Scheduler(store).run_once()
    got = np.array([
        store.pod_groups[f"default/{n}"].status.phase == "Inqueue"
        for n, _ in specs
    ])

    # Same scenario, dense: slots [cpu milli, mem bytes], 1.2x budget.
    min_res = np.array([
        [4000.0, Gi(1)],
        [np.nan, np.nan],
        [6000.0, Gi(1)],
        [4000.0, Gi(1)],
    ], np.float32)
    want = np.asarray(__import__("volcano_tpu.oracle", fromlist=["x"]).oracle_enqueue(
        min_res=min_res,
        queue_of_group=[0, 0, 0, 0],
        group_order=[0, 1, 2, 3],
        idle_budget=[12000.0, Gi(12)],
        queue_caps=np.full((1, 2), np.inf, np.float32),
        queue_alloc=np.zeros((1, 2), np.float32),
        eps=EPS, scalar_slot=NOSCAL,
    ))
    assert want.tolist() == [True, True, True, False]
    np.testing.assert_array_equal(got, want)


def test_oracle_backfill_parity_with_fast_cycle(monkeypatch):
    """backfill.go: zero-request tasks of Inqueue groups land on the
    first predicate-feasible node in node order, no resource charge —
    fast cycle and oracle_backfill agree."""
    from volcano_tpu.oracle import oracle_backfill

    store = ClusterStore()
    store.add_node(Node(name="n0", allocatable={"cpu": "2",
                                                "memory": "2Gi"}))
    store.add_node(Node(name="n1", allocatable={"cpu": "2",
                                                "memory": "2Gi"},
                        labels={"disk": "ssd"}))
    store.add_pod_group(PodGroup(name="be", min_member=1))
    # Zero-request pod that only tolerates the labeled node.
    store.add_pod(Pod(
        name="sweeper",
        annotations={GROUP_NAME_ANNOTATION: "be"},
        containers=[],
        node_selector={"disk": "ssd"},
    ))
    monkeypatch.setenv("VOLCANO_TPU_FASTPATH", "1")
    Scheduler(store).run_once()
    pod = next(iter(store.pods.values()))
    assert pod.node_name == "n1"
    # Node resources untouched (BestEffort charges nothing).
    assert store.nodes["n1"].used.milli_cpu == 0

    be_feasible = np.array([[False, True]])
    got = oracle_backfill(be_feasible, group_inqueue=[True],
                          task_group=[0])
    assert got.tolist() == [1]
    assert f"node-{got[0]}" or True  # index 1 == n1 by construction


@pytest.mark.parametrize("seed", range(FUZZ_SEEDS_SMALL))
def test_fast_vs_object_victims_with_scalar_resources(seed, monkeypatch):
    """Extended scalar resources ride the reclaim proportion walk
    (Resource dict-entry semantics — zeroed entries persist, subtrahend
    keys join the dict): fast (incl. the native engine) and object paths
    must still agree."""
    rng = np.random.default_rng(1000 + seed)
    store = ClusterStore()
    store.add_priority_class(PriorityClass(name="low", value=100))
    store.add_priority_class(PriorityClass(name="high", value=10000))
    store.add_queue(Queue(name="victim", weight=1))
    store.add_queue(Queue(name="premium", weight=9))
    for i in range(4):
        store.add_node(Node(
            name=f"node-{i:03d}",
            allocatable={"cpu": "16", "memory": "64Gi",
                         "tpu.dev/chips": 8},
        ))
    g = 0
    for i in range(4):
        for s in range(3):
            chips = int(rng.choice([0, 1, 2]))
            res = {"cpu": "4", "memory": "8Gi"}
            if chips:
                res["tpu.dev/chips"] = chips
            pg = PodGroup(name=f"fill-{g:03d}", min_member=1,
                          queue="victim")
            store.add_pod_group(pg)
            store.add_pod(Pod(
                name=f"fill-{g:03d}-0",
                annotations={GROUP_NAME_ANNOTATION: pg.name},
                containers=[res],
                phase=PodPhase.Running, node_name=f"node-{i:03d}",
                priority_class="low", priority=100,
            ))
            g += 1
    for j in range(3):
        chips = int(rng.choice([0, 2]))
        res = {"cpu": "8", "memory": "8Gi"}
        if chips:
            res["tpu.dev/chips"] = chips
        pg = PodGroup(name=f"hi-{j:03d}", min_member=1, queue="premium")
        store.add_pod_group(pg)
        store.add_pod(Pod(
            name=f"hi-{j:03d}-0",
            annotations={GROUP_NAME_ANNOTATION: pg.name},
            containers=[res], priority_class="high", priority=10000,
        ))
    stores = {}
    for mode, env in (("fast", "1"), ("object", "0")):
        import copy as _copy
        monkeypatch.setenv("VOLCANO_TPU_FASTPATH", env)
        # Rebuild an identical store per mode from the same seed.
        if mode == "fast":
            stores[mode] = store
        else:
            rng2 = np.random.default_rng(1000 + seed)
            s2 = ClusterStore()
            s2.add_priority_class(PriorityClass(name="low", value=100))
            s2.add_priority_class(PriorityClass(name="high",
                                                value=10000))
            s2.add_queue(Queue(name="victim", weight=1))
            s2.add_queue(Queue(name="premium", weight=9))
            for i in range(4):
                s2.add_node(Node(
                    name=f"node-{i:03d}",
                    allocatable={"cpu": "16", "memory": "64Gi",
                                 "tpu.dev/chips": 8},
                ))
            g2 = 0
            for i in range(4):
                for s in range(3):
                    chips = int(rng2.choice([0, 1, 2]))
                    res = {"cpu": "4", "memory": "8Gi"}
                    if chips:
                        res["tpu.dev/chips"] = chips
                    pg = PodGroup(name=f"fill-{g2:03d}", min_member=1,
                                  queue="victim")
                    s2.add_pod_group(pg)
                    s2.add_pod(Pod(
                        name=f"fill-{g2:03d}-0",
                        annotations={GROUP_NAME_ANNOTATION: pg.name},
                        containers=[res],
                        phase=PodPhase.Running,
                        node_name=f"node-{i:03d}",
                        priority_class="low", priority=100,
                    ))
                    g2 += 1
            for j in range(3):
                chips = int(rng2.choice([0, 2]))
                res = {"cpu": "8", "memory": "8Gi"}
                if chips:
                    res["tpu.dev/chips"] = chips
                pg = PodGroup(name=f"hi-{j:03d}", min_member=1,
                              queue="premium")
                s2.add_pod_group(pg)
                s2.add_pod(Pod(
                    name=f"hi-{j:03d}-0",
                    annotations={GROUP_NAME_ANNOTATION: pg.name},
                    containers=[res], priority_class="high",
                    priority=10000,
                ))
            stores[mode] = s2
        Scheduler(stores[mode], conf_str=EVICT_CONF).run_once()
    assert (evicted_keys(stores["fast"])
            == evicted_keys(stores["object"]))


@pytest.mark.parametrize("seed", range(FUZZ_SEEDS_SMALL))
def test_drive_yield_path_parity(seed, monkeypatch):
    """The C reclaim driver yields tasks it cannot handle exactly
    (host ports here) back to a Python turn; fast and object paths must
    still produce identical victim sets, and the yield path must
    actually run (guarded by instrumentation)."""
    import volcano_tpu.fastpath_evict as FE

    def build():
        rng = np.random.default_rng(3000 + seed)
        store = ClusterStore()
        store.add_priority_class(PriorityClass(name="low", value=100))
        store.add_priority_class(PriorityClass(name="high", value=10000))
        store.add_queue(Queue(name="victim", weight=1))
        store.add_queue(Queue(name="premium", weight=9))
        for i in range(4):
            store.add_node(Node(
                name=f"node-{i:03d}",
                allocatable={"cpu": "16", "memory": "64Gi", "pods": 64},
            ))
        g = 0
        for i in range(4):
            for s in range(2):
                pg = PodGroup(name=f"fill-{g:03d}", min_member=1,
                              queue="victim")
                store.add_pod_group(pg)
                store.add_pod(Pod(
                    name=f"fill-{g:03d}-0",
                    annotations={GROUP_NAME_ANNOTATION: pg.name},
                    containers=[{"cpu": str(int(rng.choice([4, 8]))),
                                 "memory": "8Gi"}],
                    phase=PodPhase.Running, node_name=f"node-{i:03d}",
                    priority_class="low", priority=100,
                ))
                g += 1
        for j in range(4):
            pg = PodGroup(name=f"hi-{j:03d}", min_member=1,
                          queue="premium")
            store.add_pod_group(pg)
            # Half the reclaimers carry host ports -> non-plain feature
            # -> the C drive must yield them to the Python turn.
            ports = [9000 + j] if j % 2 == 0 else []
            store.add_pod(Pod(
                name=f"hi-{j:03d}-0",
                annotations={GROUP_NAME_ANNOTATION: pg.name},
                containers=[{"cpu": "8", "memory": "8Gi"}],
                host_ports=ports,
                priority_class="high", priority=10000,
            ))
        return store

    yields = {"n": 0}
    orig = FE.FastEvictor._drive_python_turn

    def counting(self, *a, **k):
        yields["n"] += 1
        return orig(self, *a, **k)

    monkeypatch.setattr(FE.FastEvictor, "_drive_python_turn", counting)
    res = {}
    for mode, env in (("fast", "1"), ("object", "0")):
        monkeypatch.setenv("VOLCANO_TPU_FASTPATH", env)
        store = build()
        Scheduler(store, conf_str=EVICT_CONF).run_once()
        res[mode] = set(getattr(store.evictor, "evicts", []))
    assert res["fast"] == res["object"], (
        f"seed {seed}: {res['fast'] ^ res['object']}"
    )
    from volcano_tpu.native import reclaim_lib
    if reclaim_lib() is not None and seed < 4:
        # Yield-exercise guard only on the curated seeds: at arbitrary
        # seeds the drained-top-job quirk can legitimately kill the
        # queue before any ported task's turn (no yield fires) — the
        # parity assertion above is the real check for every seed.
        assert yields["n"] > 0, "yield path never exercised"
