"""Store event-handler semantics, per event type.

The analog of the reference's scheduler-cache handler tests
(``pkg/scheduler/cache/event_handlers_test.go``): each informer event
type (AddPod/UpdatePod/DeletePod, Add/Update/DeletePodGroup,
Add/Update/DeleteQueue, Add/Update/DeleteNode) has defined effects on
the cache's accounting — node usage, job task sets, mirror rows — and
on the watcher fan-out.  The mirror-churn fuzz (test_mirror_fuzz.py)
covers random interleavings; these tests pin the per-event semantics
the fuzz can only exercise implicitly.
"""

import copy

import numpy as np
import pytest

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    PodPhase,
    PriorityClass,
    Queue,
    TaskStatus,
)
from volcano_tpu.cache import ClusterStore


def store_with_node(cpu="8", mem="16Gi") -> ClusterStore:
    s = ClusterStore()
    s.add_node(Node(name="n0", allocatable={"cpu": cpu, "memory": mem,
                                            "pods": 110}))
    return s


def running_pod(name="p0", node="n0", cpu="2", group="g") -> Pod:
    return Pod(
        name=name,
        annotations={GROUP_NAME_ANNOTATION: group},
        containers=[{"cpu": cpu, "memory": "1Gi"}],
        phase=PodPhase.Running,
        node_name=node,
    )


def watched(store):
    seen = []
    store.watch(lambda kind, event, obj: seen.append((kind, event)))
    return seen


# ------------------------------------------------------------- pod events


def test_add_pod_charges_node():
    s = store_with_node()
    s.add_pod_group(PodGroup(name="g", min_member=1))
    s.add_pod(running_pod(cpu="2"))
    assert s.nodes["n0"].used.milli_cpu == 2000
    assert s.nodes["n0"].idle.milli_cpu == 6000
    job = s.jobs["default/g"]
    assert len(job.tasks) == 1


def test_add_pending_pod_charges_nothing():
    s = store_with_node()
    s.add_pod_group(PodGroup(name="g", min_member=1))
    pod = Pod(name="p0", annotations={GROUP_NAME_ANNOTATION: "g"},
              containers=[{"cpu": "2", "memory": "1Gi"}])
    s.add_pod(pod)
    assert s.nodes["n0"].used.milli_cpu == 0
    m = s.mirror
    row = m.p_row[pod.uid]
    assert m.p_status[row] == int(TaskStatus.Pending)
    assert m.p_node[row] == -1


def test_update_pod_phase_transition_updates_status_only():
    """updateTask analog: same spec, new phase -> the mirror row is
    REUSED (no tombstone) and only dynamic state changes."""
    s = store_with_node()
    s.add_pod_group(PodGroup(name="g", min_member=1))
    pod = running_pod()
    s.add_pod(pod)
    row = s.mirror.p_row[pod.uid]
    upd = copy.copy(pod)
    upd.phase = PodPhase.Succeeded
    s.update_pod(upd)
    assert s.mirror.p_row[pod.uid] == row  # row reused
    assert s.mirror.p_status[row] == int(TaskStatus.Succeeded)
    # Succeeded pods release node usage (terminated resources free).
    assert s.nodes["n0"].used.milli_cpu == 0


def test_update_pod_spec_change_tombstones_and_readds():
    """A spec (resource) change is a delete+add in the cache: the old
    row is tombstoned, a fresh row carries the new request."""
    s = store_with_node()
    s.add_pod_group(PodGroup(name="g", min_member=1))
    pod = running_pod(cpu="2")
    s.add_pod(pod)
    old_row = s.mirror.p_row[pod.uid]
    # A fresh object (no cached feature blob), as an informer update
    # carrying a changed spec would arrive — copy.copy would carry the
    # bind/evict copy-on-write feature cache and take the same-spec path.
    upd = Pod(name=pod.name, uid=pod.uid,
              annotations=dict(pod.annotations),
              containers=[{"cpu": "4", "memory": "1Gi"}],
              phase=pod.phase, node_name=pod.node_name)
    s.update_pod(upd)
    new_row = s.mirror.p_row[pod.uid]
    assert new_row != old_row
    assert s.mirror.p_pod[old_row] is None
    assert s.mirror.p_pod_nones >= 1
    assert s.nodes["n0"].used.milli_cpu == 4000


def test_update_pod_node_move_recharges():
    s = store_with_node()
    s.add_node(Node(name="n1", allocatable={"cpu": "8", "memory": "16Gi"}))
    s.add_pod_group(PodGroup(name="g", min_member=1))
    pod = running_pod(cpu="2", node="n0")
    s.add_pod(pod)
    moved = copy.copy(pod)
    moved.node_name = "n1"
    s.update_pod(moved)
    assert s.nodes["n0"].used.milli_cpu == 0
    assert s.nodes["n1"].used.milli_cpu == 2000


def test_delete_pod_releases_everything():
    s = store_with_node()
    s.add_pod_group(PodGroup(name="g", min_member=1))
    pod = running_pod(cpu="2")
    s.add_pod(pod)
    row = s.mirror.p_row[pod.uid]
    s.delete_pod(pod)
    assert s.nodes["n0"].used.milli_cpu == 0
    assert pod.uid not in s.pods
    assert not s.mirror.p_alive[row]
    assert s.mirror.p_pod[row] is None
    # Job drops once taskless AND podgroup-less; with the PG it stays.
    assert "default/g" in s.jobs
    assert len(s.jobs["default/g"].tasks) == 0


def test_delete_unknown_pod_is_noop():
    s = store_with_node()
    s.delete_pod(running_pod(name="ghost"))
    assert len(s.pods) == 0


def test_pod_added_before_node_adopts_on_node_arrival():
    """Orphan adoption (event_handlers addTask placeholder-node path):
    a running pod naming a node the cache hasn't seen charges it
    retroactively when the node arrives."""
    s = ClusterStore()
    s.add_pod_group(PodGroup(name="g", min_member=1))
    pod = running_pod(node="late-node", cpu="2")
    s.add_pod(pod)
    s.add_node(Node(name="late-node",
                    allocatable={"cpu": "8", "memory": "16Gi"}))
    assert s.nodes["late-node"].used.milli_cpu == 2000
    m = s.mirror
    row = m.p_row[pod.uid]
    assert m.n_name[m.p_node[row]] == "late-node"


# -------------------------------------------------------- podgroup events


def test_add_pod_group_links_job_and_priority():
    s = store_with_node()
    s.add_priority_class(PriorityClass(name="high", value=5000))
    s.add_pod_group(PodGroup(name="g", min_member=3,
                             priority_class="high"))
    job = s.jobs["default/g"]
    assert job.pod_group is not None
    assert job.priority == 5000
    row = s.mirror.j_row["default/g"]
    assert s.mirror.j_minav[row] == 3
    assert s.mirror.j_prio[row] == 5000


def test_update_pod_group_changes_min_member_live():
    s = store_with_node()
    s.add_pod_group(PodGroup(name="g", min_member=1))
    pg = s.pod_groups["default/g"]
    upd = copy.copy(pg)
    upd.min_member = 4
    s.update_pod_group(upd)
    assert s.mirror.j_minav[s.mirror.j_row["default/g"]] == 4
    assert s.jobs["default/g"].pod_group.min_member == 4


def test_update_pod_group_preserves_status_phase():
    s = store_with_node()
    s.add_pod_group(PodGroup(name="g", min_member=1))
    pg = s.pod_groups["default/g"]
    pg.status.phase = "Inqueue"
    s.update_pod_group(pg)
    assert s.pod_groups["default/g"].status.phase == "Inqueue"


def test_delete_pod_group_keeps_job_while_tasks_remain():
    """DeletePodGroup with live tasks: the JobInfo survives (tasks still
    need accounting); without tasks it drops entirely."""
    s = store_with_node()
    s.add_pod_group(PodGroup(name="g", min_member=1))
    pod = running_pod()
    s.add_pod(pod)
    s.delete_pod_group("default/g")
    assert "default/g" in s.jobs  # tasks pin it
    assert s.jobs["default/g"].pod_group is None
    s.delete_pod(s.pods[pod.uid])
    s.delete_pod_group("default/g")
    assert "default/g" not in s.jobs


def test_delete_pod_group_removes_mirror_row():
    s = store_with_node()
    s.add_pod_group(PodGroup(name="g", min_member=1))
    assert "default/g" in s.mirror.j_row
    s.delete_pod_group("default/g")
    assert not s.mirror.j_alive[s.mirror.j_row.get("default/g", 0)] or \
        "default/g" not in s.mirror.j_row


# ----------------------------------------------------------- queue events


def test_add_queue_visible_in_snapshot():
    s = store_with_node()
    s.add_queue(Queue(name="q1", weight=4))
    snap = s.snapshot()
    assert "q1" in snap.queues
    assert snap.queues["q1"].weight == 4


def test_update_queue_weight_applies():
    s = store_with_node()
    s.add_queue(Queue(name="q1", weight=1))
    s.update_queue(Queue(name="q1", weight=8))
    assert s.queues["q1"].weight == 8


def test_delete_queue_removes_it():
    s = store_with_node()
    s.add_queue(Queue(name="q1", weight=1))
    s.delete_queue("q1")
    assert "q1" not in s.queues
    # Default queue always survives.
    assert "default" in s.queues


# ------------------------------------------------------------ node events


def test_update_node_allocatable_reflects_in_idle():
    s = store_with_node(cpu="8")
    s.add_pod_group(PodGroup(name="g", min_member=1))
    s.add_pod(running_pod(cpu="2"))
    s.update_node(Node(name="n0",
                       allocatable={"cpu": "16", "memory": "16Gi"}))
    assert s.nodes["n0"].idle.milli_cpu == 14000
    assert s.nodes["n0"].used.milli_cpu == 2000


def test_delete_node_keeps_pod_records():
    """Node deletion leaves its pods in the cache (the reference keeps
    tasks; kubelet/informer deletes them separately)."""
    s = store_with_node()
    s.add_pod_group(PodGroup(name="g", min_member=1))
    pod = running_pod(cpu="2")
    s.add_pod(pod)
    s.delete_node("n0")
    assert "n0" not in s.nodes
    assert pod.uid in s.pods


# --------------------------------------------------------------- watchers


@pytest.mark.parametrize("op,kind,event", [
    ("add_pod", "Pod", "add"),
    ("update_pod", "Pod", "update"),
    ("delete_pod", "Pod", "delete"),
    ("add_pod_group", "PodGroup", "add"),
    ("update_pod_group", "PodGroup", "update"),
    ("delete_pod_group", "PodGroup", "delete"),
])
def test_watcher_fires_per_event_type(op, kind, event):
    s = store_with_node()
    pg = PodGroup(name="g", min_member=1)
    pod = running_pod()
    if op in ("update_pod", "delete_pod"):
        s.add_pod_group(pg)
        s.add_pod(pod)
    elif op in ("update_pod_group", "delete_pod_group"):
        s.add_pod_group(pg)
    seen = watched(s)
    if op == "add_pod":
        s.add_pod_group(pg)
        s.add_pod(pod)
    elif op == "update_pod":
        s.update_pod(copy.copy(pod))
    elif op == "delete_pod":
        s.delete_pod(pod)
    elif op == "add_pod_group":
        s.add_pod_group(pg)
    elif op == "update_pod_group":
        s.update_pod_group(pg)
    elif op == "delete_pod_group":
        s.delete_pod_group("default/g")
    assert (kind, event) in seen


# ----------------------------------------------------- status transitions


@pytest.mark.parametrize("phase,expected_status", [
    (PodPhase.Pending, TaskStatus.Pending),
    (PodPhase.Running, TaskStatus.Running),
    (PodPhase.Succeeded, TaskStatus.Succeeded),
    (PodPhase.Failed, TaskStatus.Failed),
])
def test_phase_to_task_status_mapping(phase, expected_status):
    """The pod-phase -> TaskStatus table (api/helpers.go getTaskStatus),
    as observed through the mirror after an update event."""
    s = store_with_node()
    s.add_pod_group(PodGroup(name="g", min_member=1))
    pod = Pod(name="p0", annotations={GROUP_NAME_ANNOTATION: "g"},
              containers=[{"cpu": "1", "memory": "1Gi"}],
              phase=phase,
              node_name="n0" if phase != PodPhase.Pending else None)
    s.add_pod(pod)
    row = s.mirror.p_row[pod.uid]
    assert s.mirror.p_status[row] == int(expected_status)


def test_deleting_pod_becomes_releasing():
    s = store_with_node()
    s.add_pod_group(PodGroup(name="g", min_member=1))
    pod = running_pod(cpu="2")
    s.add_pod(pod)
    upd = copy.copy(pod)
    upd.deleting = True
    s.update_pod(upd)
    row = s.mirror.p_row[pod.uid]
    assert s.mirror.p_status[row] == int(TaskStatus.Releasing)
    node = s.nodes["n0"]
    # Releasing stays in used (NodeInfo semantics) and in releasing.
    assert node.used.milli_cpu == 2000
    assert node.releasing.milli_cpu == 2000


def test_event_trails_capped_fifo():
    """The event-trail cache evicts oldest objects first at the cap and
    keeps per-object trails bounded."""
    s = ClusterStore()
    cap = s.MAX_EVENT_OBJECTS
    s.record_events([(f"Pod/default/x-{i}", "R", "m")
                     for i in range(cap + 10)])
    assert len(s._events) == cap
    assert not s.events_for("Pod/default/x-0")  # oldest evicted
    assert s.events_for(f"Pod/default/x-{cap + 9}")
    for i in range(s.EVENTS_PER_OBJECT + 5):
        s.record_event("Pod/default/x-5000", "R", f"m{i}")
    assert len(s.events_for("Pod/default/x-5000")) <= s.EVENTS_PER_OBJECT


def test_event_dedupe_increments_count():
    s = ClusterStore()
    s.record_event("Pod/default/a", "FailedScheduling", "no fit")
    s.record_event("Pod/default/a", "FailedScheduling", "no fit")
    trail = s.events_for("Pod/default/a")
    assert len(trail) == 1
    assert trail[0]["count"] == 2
