"""Controller-plane job cache semantics.

The analog of ``pkg/controllers/cache/cache_test.go`` (846 LoC of
Add/Update/Delete/Get jobInfo coverage).  PARITY.md folds the
reference's separate controller cache into the store's batch-job index;
these tests pin the surface the controllers rely on: record lifecycle
and events, version monotonicity across kills, controlled-resources
persistence (plugin idempotency), finalizer handling, the retry-keys
requeue for jobs parked on missing IO, and suspend/resume commands
through the bus API.
"""

import pytest

from volcano_tpu.api import Node, PodGroupPhase
from volcano_tpu.cache import ClusterStore
from volcano_tpu.controllers import (
    ControllerManager,
    Job,
    JobController,
    TaskSpec,
)
from volcano_tpu.controllers.apis import Command, JobPhase, VolumeSpec


def make_store():
    s = ClusterStore()
    s.add_node(Node(name="n0", allocatable={"cpu": "16", "memory": "32Gi",
                                            "pods": 110}))
    return s


def make_job(name="j1", replicas=2, **kw):
    return Job(name=name, min_available=kw.pop("min_available", replicas),
               tasks=[TaskSpec(name="w", replicas=replicas,
                               containers=[{"cpu": "1", "memory": "1Gi"}])],
               **kw)


# --------------------------------------------------------- record lifecycle


def test_add_get_update_delete_roundtrip():
    s = make_store()
    job = make_job()
    s.add_batch_job(job)
    assert s.batch_jobs["default/j1"] is job
    job.min_available = 1
    s.update_batch_job(job)
    assert s.batch_jobs["default/j1"].min_available == 1
    s.delete_batch_job("default/j1")
    assert "default/j1" not in s.batch_jobs


def test_add_fires_job_watch_events():
    s = make_store()
    seen = []
    s.watch(lambda kind, event, obj: seen.append((kind, event)))
    job = make_job()
    s.add_batch_job(job)
    s.update_batch_job(job)
    s.delete_batch_job(job.key)
    assert ("Job", "add") in seen
    assert ("Job", "update") in seen
    assert ("Job", "delete") in seen


def test_delete_unknown_job_is_noop():
    s = make_store()
    s.delete_batch_job("default/ghost")  # no raise
    assert not s.batch_jobs


def test_jobs_namespaced():
    s = make_store()
    s.add_batch_job(make_job())
    s.add_batch_job(Job(name="j1", namespace="other", min_available=1,
                        tasks=[TaskSpec(name="w", replicas=1,
                                        containers=[{"cpu": "1"}])]))
    assert set(s.batch_jobs) == {"default/j1", "other/j1"}


# --------------------------------------------------- version + finalizers


def test_version_monotonic_across_kills():
    """Each kill bumps the job version (stale pod events then degrade
    to sync — job_controller_handler.go:154-178)."""
    s = make_store()
    jc = JobController(s)
    job = make_job()
    jc.sync_job(job, None)
    versions = [job.status.version]
    for _ in range(3):
        jc.kill_job(job, retain_phases=set(), update_status=None)
        versions.append(job.status.version)
    assert versions == sorted(versions)
    assert len(set(versions)) == len(versions)


def test_initiate_adds_cleanup_finalizer_once():
    s = make_store()
    jc = JobController(s)
    job = make_job()
    jc.sync_job(job, None)
    jc.sync_job(job, None)
    assert job.finalizers.count("volcano-tpu/job-cleanup") == 1


def test_controlled_resources_keep_plugins_idempotent():
    """Plugin on_job_add hooks run once per job generation, guarded by
    Status.ControlledResources (svc.go:128 semantics)."""
    s = make_store()
    jc = JobController(s)
    job = make_job(plugins={"env": []})
    jc.sync_job(job, None)
    markers = dict(job.status.controlled_resources)
    assert any(k.startswith("plugin-") for k in markers)
    jc.sync_job(job, None)
    assert job.status.controlled_resources == markers


# ------------------------------------------------------------- retry keys


def test_missing_io_parks_job_and_reprocesses():
    """A job naming a nonexistent claim stays Pending; process_all
    requeues it (the rate-limited workqueue requeue analog) and it
    proceeds the moment the claim appears."""
    s = make_store()
    cm = ControllerManager(s)
    job = make_job(volumes=[VolumeSpec(mount_path="/d",
                                       volume_claim_name="later")])
    s.add_batch_job(job)
    cm.process()
    assert "default/j1" not in s.pod_groups
    cm.process()  # still parked, no crash, still retried
    assert "default/j1" not in s.pod_groups
    s.put_pvc("default", "later", {"storage": "1Gi"})
    cm.process()
    assert "default/j1" in s.pod_groups


# --------------------------------------------------------------- commands


def test_suspend_resume_via_bus_commands():
    """AbortJob then ResumeJob through the command bus: pods die with
    the abort (non-retained) and come back after resume."""
    s = make_store()
    cm = ControllerManager(s)
    job = make_job(replicas=2)
    s.add_batch_job(job)
    cm.process()
    pg = s.pod_groups["default/j1"]
    pg.status.phase = PodGroupPhase.Inqueue.value
    s.update_pod_group(pg)
    s._notify("PodGroup", "status", pg)  # the scheduler's close signal
    cm.process()
    pods = [p for p in s.pods.values() if p.owner_job == "default/j1"]
    assert len(pods) == 2

    s.add_command(Command(action="AbortJob", target_kind="Job",
                          target_name="j1", name="c1"))
    cm.process()
    job = s.batch_jobs["default/j1"]
    assert job.status.state.phase in (JobPhase.Aborting.value,
                                      JobPhase.Aborted.value)
    assert all(p.deleting for p in s.pods.values()
               if p.owner_job == "default/j1")

    s.add_command(Command(action="ResumeJob", target_kind="Job",
                          target_name="j1", name="c2"))
    for _ in range(4):
        cm.process()
    job = s.batch_jobs["default/j1"]
    assert job.status.state.phase not in (JobPhase.Aborted.value,
                                          JobPhase.Aborting.value)


def test_job_deletion_runs_cleanup_cascade():
    s = make_store()
    cm = ControllerManager(s)
    job = make_job(replicas=1,
                   volumes=[VolumeSpec(mount_path="/d",
                                       volume_claim={"storage": "1Gi"})])
    s.add_batch_job(job)
    cm.process()
    pg = s.pod_groups["default/j1"]
    pg.status.phase = PodGroupPhase.Inqueue.value
    s.update_pod_group(pg)
    s._notify("PodGroup", "status", pg)
    cm.process()
    assert s.pvcs
    s.delete_batch_job("default/j1")
    cm.process()
    assert "default/j1" not in s.pod_groups
    assert not s.pvcs  # owner-ref cascade
