"""Leader election (ha.py) and store checkpoint/restore (persistence.py)."""

import threading
import time

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    PriorityClass,
    Queue,
)
from volcano_tpu.cache import ClusterStore
from volcano_tpu.ha import LeaderElector
from volcano_tpu.persistence import load_store, save_store
from volcano_tpu.scheduler import Scheduler


def _populated_store():
    store = ClusterStore()
    store.add_node(Node(name="n0", allocatable={"cpu": "8", "memory": "16Gi"}))
    store.add_node(Node(name="n1", allocatable={"cpu": "8", "memory": "16Gi"},
                        labels={"zone": "z1"}))
    store.add_queue(Queue(name="gold", weight=4))
    store.add_priority_class(PriorityClass(name="high", value=100))
    store.add_pod_group(PodGroup(name="pg", min_member=2, queue="gold",
                                 priority_class="high"))
    for i in range(2):
        store.add_pod(Pod(
            name=f"p{i}", containers=[{"cpu": "1", "memory": "1Gi"}],
            annotations={GROUP_NAME_ANNOTATION: "pg"},
        ))
    return store


def test_checkpoint_roundtrip_schedules_identically(tmp_path):
    path = str(tmp_path / "state.ckpt")
    a = _populated_store()
    save_store(a, path)
    b = load_store(path)
    assert set(b.pods) == set(a.pods)
    assert set(b.pod_groups) == set(a.pod_groups)
    assert set(b.raw_queues) == set(a.raw_queues)
    assert b.jobs["default/pg"].priority == 100
    Scheduler(a).run_once()
    Scheduler(b).run_once()
    assert b.binder.binds == a.binder.binds


def test_checkpoint_after_scheduling(tmp_path):
    """Bound state survives save/load (pods keep node_name)."""
    path = str(tmp_path / "state.ckpt")
    a = _populated_store()
    Scheduler(a).run_once()
    assert len(a.binder.binds) == 2
    save_store(a, path)
    b = load_store(path)
    bound = [p for p in b.pods.values() if p.node_name]
    assert len(bound) == 2
    # A new cycle finds nothing pending.
    Scheduler(b).run_once()
    assert len(b.binder.binds) == 0  # fresh FakeBinder, nothing re-bound


def test_leader_election_single_holder(tmp_path):
    lease = str(tmp_path / "lease")
    a = LeaderElector(lease, identity="a", lease_duration=0.5,
                      renew_deadline=0.3, retry_period=0.05)
    b = LeaderElector(lease, identity="b", lease_duration=0.5,
                      renew_deadline=0.3, retry_period=0.05)
    assert a.try_acquire()
    assert not b.try_acquire()
    assert a.renew()
    # Expired lease transfers.
    time.sleep(0.6)
    assert b.try_acquire()
    assert not a.renew()


def test_leader_election_failover(tmp_path):
    lease = str(tmp_path / "lease")
    events = []
    a = LeaderElector(lease, identity="a", lease_duration=0.4,
                      renew_deadline=0.2, retry_period=0.05)
    b = LeaderElector(lease, identity="b", lease_duration=0.4,
                      renew_deadline=0.2, retry_period=0.05)
    tb = threading.Thread(
        target=lambda: b.run(lambda: events.append("b-lead"),
                             lambda: events.append("b-stop"), once=True),
        daemon=True,
    )
    assert a.try_acquire()
    tb.start()
    time.sleep(0.3)
    assert not b.is_leader  # a holds
    a.stop()  # releases the lease
    time.sleep(0.5)
    assert "b-lead" in events
    b.stop()
    tb.join(timeout=2)


def test_healthz_unhealthy_after_repeated_cycle_failures(monkeypatch):
    """Repeated scheduling-cycle failures (a crashed device runtime is
    unrecoverable in-process) flip /healthz to 503 so a supervisor or the
    HA standby takes over (SURVEY.md 5.3)."""
    import urllib.request

    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.service import Service

    svc = Service(simulate=True, schedule_period=0.02,
                  controller_period=0.05)
    monkeypatch.setattr(
        Scheduler, "run_once",
        lambda self: (_ for _ in ()).throw(RuntimeError("device gone")),
    )
    port = svc.start(http_port=0)
    try:
        import time

        deadline = time.time() + 10
        status = 200
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=2
                ) as resp:
                    status = resp.status
            except urllib.error.HTTPError as err:
                status = err.code
            if status == 503:
                break
            time.sleep(0.05)
        assert status == 503
    finally:
        svc.stop()


def test_checkpoint_restore_preserves_topology_and_affinity(tmp_path):
    """Checkpoint/restore round-trips a store with slice topology,
    affinity terms, and bound pods; the restored mirror schedules the
    remaining pending pods identically to the original."""
    from volcano_tpu.api.spec import AffinityTerm
    from volcano_tpu.persistence import load_store, save_store
    from volcano_tpu.scheduler import Scheduler

    def build():
        from volcano_tpu.api import (GROUP_NAME_ANNOTATION, Node, Pod,
                                     PodGroup)
        from volcano_tpu.cache import ClusterStore

        store = ClusterStore()
        for i in range(4):
            store.add_node(Node(
                name=f"n{i}",
                allocatable={"cpu": "4", "memory": "8Gi", "pods": 16},
                topology={"volcano-tpu/slice": f"s{i // 2}"},
            ))
        term = AffinityTerm(match_labels={"app": "x"},
                            topology_key="volcano-tpu/slice")
        store.add_pod_group(PodGroup(name="g", min_member=4))
        for k in range(4):
            store.add_pod(Pod(
                name=f"p{k}", labels={"app": "x"},
                containers=[{"cpu": "1", "memory": "1Gi"}],
                annotations={GROUP_NAME_ANNOTATION: "g"},
                affinity=[term],
            ))
        return store

    a = build()
    path = tmp_path / "state.ckpt"
    save_store(a, str(path))
    b = load_store(str(path))
    Scheduler(a).run_once()
    Scheduler(b).run_once()
    assert dict(b.binder.binds) == dict(a.binder.binds)
    assert len(b.binder.binds) == 4
    # All in one slice (the affinity term resolved over restored topology).
    assert len({int(n[1]) // 2 for n in b.binder.binds.values()}) == 1


def test_checkpoint_roundtrips_claims_and_policies(tmp_path):
    """PVC records (incl. Bound state + node pins), network policies,
    and the volume-pod counter survive checkpoint/restore — a restored
    cluster must not wedge volume jobs or lose claim placements."""
    from volcano_tpu.api import GROUP_NAME_ANNOTATION, Node, Pod, PodGroup
    from volcano_tpu.cache import ClusterStore
    from volcano_tpu.persistence import load_store, save_store
    from volcano_tpu.scheduler import Scheduler

    store = ClusterStore()
    store.add_node(Node(name="n0", allocatable={"cpu": "8",
                                                "memory": "16Gi"}))
    store.put_pvc("default", "user-data", {"storage": "5Gi"})
    store.put_network_policy("default", "job-a",
                             {"pod_selector": {"k": "v"},
                              "ingress_from": [{"k": "v"}],
                              "policy_types": ["Ingress"]})
    store.add_pod_group(PodGroup(name="g", min_member=1))
    store.add_pod(Pod(
        name="p0",
        containers=[{"cpu": "1", "memory": "1Gi"}],
        annotations={GROUP_NAME_ANNOTATION: "g"},
        volumes=[("user-data", "/data")],
    ))
    Scheduler(store).run_once()
    assert store.pvcs["default/user-data"]["phase"] == "Bound"
    assert store.n_volume_pods == 1

    path = str(tmp_path / "state.ckpt")
    save_store(store, path)
    restored = load_store(path)
    assert restored.pvcs["default/user-data"]["phase"] == "Bound"
    assert restored.pvcs["default/user-data"]["node"] == "n0"
    assert restored.network_policies["default/job-a"][
        "policy_types"] == ["Ingress"]
    assert restored.n_volume_pods == 1


def test_leader_election_adversarial_two_processes_kill_mid_cycle(tmp_path):
    """Two REAL scheduler worker processes contend for the file lease;
    the active leader is SIGKILLed mid-cycle (no release, no cleanup —
    the lease must expire on its own).  Asserts the reference's HA
    contract (cmd/scheduler/app/server.go leaderelection):

    - single-writer history: leadership runs are contiguous with
      strictly increasing lease epochs (the fencing token each bind
      carries), and the killed identity never reappears after the
      survivor's first post-kill bind;
    - no double-bind: every pod id appears exactly once (the standby
      resynced the bound set before continuing, as a fresh reference
      leader rebuilds from the API server).
    """
    import os
    import signal
    import subprocess
    import sys

    lease = str(tmp_path / "lease")
    log = str(tmp_path / "binds.log")
    worker = os.path.join(os.path.dirname(__file__), "ha_worker.py")
    n_pods = 200

    def spawn(ident):
        return subprocess.Popen(
            [sys.executable, worker, lease, log, ident, str(n_pods)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

    def read_log():
        try:
            with open(log) as f:
                return [tuple(l.split()) for l in f if len(l.split()) == 3]
        except OSError:
            return []

    pa = spawn("A")
    pb = spawn("B")
    try:
        # Wait until one of them leads and has bound a few pods.
        deadline = time.time() + 30
        while time.time() < deadline and len(read_log()) < 5:
            time.sleep(0.05)
        recs = read_log()
        assert len(recs) >= 5, "no leader emerged within 30s"
        leader = recs[-1][0]
        kill_marker = len(recs)
        # SIGKILL the active leader mid-cycle: no release path runs.
        victim = pa if leader == "A" else pb
        victim.kill()
        victim.wait()
        killed_at = len(read_log())
        # The survivor must take over after the lease expires and make
        # progress.
        survivor = "B" if leader == "A" else "A"
        deadline = time.time() + 30
        while time.time() < deadline:
            recs = read_log()
            if sum(1 for r in recs if r[0] == survivor) >= 5:
                break
            time.sleep(0.05)
        recs = read_log()
        assert sum(1 for r in recs if r[0] == survivor) >= 5, (
            f"survivor {survivor} made no progress after leader kill "
            f"(log: {recs[killed_at:]})"
        )
    finally:
        for p in (pa, pb):
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
                p.wait()

    # Single-writer history: every leadership change carries a strictly
    # larger lease epoch (the fencing token), and an epoch is owned by
    # exactly one identity — two simultaneously-active leaders would
    # interleave records under non-increasing epochs.
    epochs = [float(r[1]) for r in recs]
    for i in range(1, len(recs)):
        if recs[i][0] != recs[i - 1][0]:
            assert epochs[i] > epochs[i - 1], (
                f"leadership switch without epoch fence at {i}: {recs}"
            )
    by_epoch = {}
    for ident, ep, _pod in recs:
        assert by_epoch.setdefault(ep, ident) == ident, (
            f"epoch {ep} shared by two identities: {recs}"
        )
    # The killed identity never reappears after the survivor takes over.
    post_kill = [r[0] for r in recs[kill_marker:]]
    if survivor in post_kill:
        first_surv = kill_marker + post_kill.index(survivor)
        dead_after = [
            r for r in recs[first_surv:] if r[0] == leader
        ]
        assert not dead_after, f"dead leader wrote after failover: {recs}"
    # No double-bind.
    pods = [r[2] for r in recs]
    dupes = {p for p in pods if pods.count(p) > 1}
    assert not dupes, f"pods bound twice across failover: {dupes}"
