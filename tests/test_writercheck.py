"""Fixture tests for vclint's writer-discipline (VCL70x) and
tuning-knob (VCL71x) families: every code must catch its seeded
violation at the exact location, the registry must resolve against the
committed tree, and the committed tree must lint clean.

Tier-1, CPU-only: pure AST analysis, nothing here touches jax.
"""

from __future__ import annotations

import textwrap
from pathlib import Path

from tools.vclint import knobcheck, writercheck
from tools.vclint.cli import _Sources, _run_knob, _run_writer
from tools.vclint.findings import finish

REPO_ROOT = Path(__file__).resolve().parent.parent


def _codes(findings, path=None):
    return [
        (f.code, f.line) for f in findings
        if not f.suppressed and (path is None or f.path == path)
    ]


def _with_registry(registry):
    """Context manager swapping WRITER_REGISTRY for a fixture one."""
    import contextlib

    @contextlib.contextmanager
    def _cm():
        saved = writercheck.WRITER_REGISTRY
        writercheck.WRITER_REGISTRY = registry
        try:
            yield
        finally:
            writercheck.WRITER_REGISTRY = saved

    return _cm()


# ------------------------------------------------- VCL701/702/703

TRIAD_FIXTURE = textwrap.dedent('''\
    class Mirror:
        def bad_writer(self, rows, val):
            self.p_status[rows] = val

        def good_writer(self, rows, val):
            self.p_status[rows] = val
            self.mark_pods_dirty(rows)
            self.audit.flow_rows(self.p_status, rows, val, "w")
            self.mutation_seq += 1

        def hop_writer(self, rows, val):
            self.p_status[rows] = val
            self._book(rows, val)

        def _book(self, rows, val):
            self.mark_pods_dirty(rows)
            self.audit.flow_rows(self.p_status, rows, val, "w")
            self.mutation_seq = self.mutation_seq + 1
''')


def test_missing_triad_legs_reported_per_code():
    reg = {
        "fix.py::Mirror.bad_writer": {
            "dirty": "self", "audit": "self", "seq": "self"},
        "fix.py::Mirror.good_writer": {
            "dirty": "self", "audit": "self", "seq": "self"},
        "fix.py::Mirror.hop_writer": {
            "dirty": "self", "audit": "self", "seq": "self"},
    }
    with _with_registry(reg):
        raw = writercheck.analyze_files([("fix.py", TRIAD_FIXTURE)])
    got = _codes(finish("fix.py", TRIAD_FIXTURE, raw))
    # bad_writer (def at line 2) misses all three legs.
    assert ("VCL701", 2) in got
    assert ("VCL702", 2) in got
    assert ("VCL703", 2) in got
    # good_writer satisfies all legs locally; hop_writer through its
    # one-hop helper — neither reports anything.
    assert [c for c in got if c[1] != 2] == []


def test_waived_legs_are_not_required():
    reg = {
        "fix.py::Mirror.bad_writer": {
            "dirty": "self",
            "audit": "caller declares the flow",
            "seq": "caller stamps once per batch",
        },
    }
    with _with_registry(reg):
        raw = writercheck.analyze_files([("fix.py", TRIAD_FIXTURE)])
    got = _codes(finish("fix.py", TRIAD_FIXTURE, raw))
    # Only the 'self' dirty leg is checked (and missed); the waived
    # audit/seq legs report nothing.
    assert ("VCL701", 2) in got
    assert not any(c[0] in ("VCL702", "VCL703") for c in got)


def test_registry_missing_function_is_vcl001():
    reg = {"fix.py::Mirror.ghost": {
        "dirty": "self", "audit": "self", "seq": "self"}}
    with _with_registry(reg):
        raw = writercheck.analyze_files([("fix.py", TRIAD_FIXTURE)])
    got = _codes(finish("fix.py", TRIAD_FIXTURE, raw))
    assert ("VCL001", 1) in got


# ------------------------------------------------------- VCL706

JOURNEY_FIXTURE = textwrap.dedent('''\
    class Mirror:
        def silent_writer(self, rows, val):
            self.p_status[rows] = val
            self.mark_pods_dirty(rows)
            self.audit.flow_rows(self.p_status, rows, val, "w")
            self.mutation_seq += 1

        def tracked_writer(self, rows, val):
            self.p_status[rows] = val
            self.mark_pods_dirty(rows)
            self.audit.flow_rows(self.p_status, rows, val, "w")
            self.journey.pod_event(self.p_uid[rows], "bound")
            self.mutation_seq += 1

        def hop_writer(self, rows, val):
            self.p_status[rows] = val
            self.mark_pods_dirty(rows)
            self.audit.flow_rows(self.p_status, rows, val, "w")
            self._capture(rows)
            self.mutation_seq += 1

        def _capture(self, rows):
            self._journey_rows(rows, "bound")
''')


def test_missing_journey_leg_is_vcl706():
    """The fourth leg: a registered writer that never captures a
    pod-journey event reports VCL706; pod_event locally or a bulk
    helper one hop away both satisfy it."""
    reg = {
        "fix.py::Mirror.silent_writer": {
            "dirty": "self", "audit": "self", "journey": "self",
            "seq": "self"},
        "fix.py::Mirror.tracked_writer": {
            "dirty": "self", "audit": "self", "journey": "self",
            "seq": "self"},
        "fix.py::Mirror.hop_writer": {
            "dirty": "self", "audit": "self", "journey": "self",
            "seq": "self"},
    }
    with _with_registry(reg):
        raw = writercheck.analyze_files([("fix.py", JOURNEY_FIXTURE)])
    got = _codes(finish("fix.py", JOURNEY_FIXTURE, raw))
    assert got == [("VCL706", 2)]


def test_waived_journey_leg_reports_nothing():
    reg = {
        "fix.py::Mirror.silent_writer": {
            "dirty": "self", "audit": "self",
            "journey": "node-only writer -- no pod transition to record",
            "seq": "self"},
    }
    with _with_registry(reg):
        raw = writercheck.analyze_files([("fix.py", JOURNEY_FIXTURE)])
    got = _codes(finish("fix.py", JOURNEY_FIXTURE, raw))
    assert not any(c[0] == "VCL706" for c in got)


# ------------------------------------------------------- VCL704

UNREGISTERED_FIXTURE = textwrap.dedent('''\
    class Sneaky:
        def direct(self, rows):
            self.p_node[rows] = -1

        def via_alias(self, m, rows):
            col = m.p_status
            col[rows] = 7

        def reads_only(self, m, rows):
            return m.p_status[rows]

        # vclint: writer-exempt -- test scaffolding, rolled back by caller
        def reviewed(self, m, rows):
            m.p_alive[rows] = False

        def __init__(self):
            self.p_status = None
''')


def test_unregistered_writer_shapes_flagged():
    with _with_registry({}):
        raw = writercheck.analyze_files(
            [("fix.py", UNREGISTERED_FIXTURE)])
    got = _codes(finish("fix.py", UNREGISTERED_FIXTURE, raw))
    # direct subscript store (line 3) and the one-level alias store
    # (line 7) are writer-shaped; the read, the exempted method, and
    # __init__ are not flagged.
    assert ("VCL704", 3) in got
    assert ("VCL704", 7) in got
    assert len([c for c in got if c[0] == "VCL704"]) == 2


# ------------------------------------------------------- VCL705

REASONLESS_FIXTURE = textwrap.dedent('''\
    class Sloppy:
        # vclint: writer-exempt
        def writer(self, m, rows):
            m.p_status[rows] = 1
''')


def test_reasonless_exemption_is_vcl705_and_unsuppressable():
    with _with_registry({}):
        raw = writercheck.analyze_files([("fix.py", REASONLESS_FIXTURE)])
    got = _codes(finish("fix.py", REASONLESS_FIXTURE, raw))
    assert ("VCL705", 2) in got

    # A suppression comment on the same line must NOT silence it.
    suppressed_src = REASONLESS_FIXTURE.replace(
        "# vclint: writer-exempt",
        "# vclint: writer-exempt  # vclint: disable=VCL705 -- nope")
    with _with_registry({}):
        raw = writercheck.analyze_files([("fix.py", suppressed_src)])
    got = _codes(finish("fix.py", suppressed_src, raw))
    assert any(c[0] == "VCL705" for c in got)


def test_free_floating_reasonless_marker_flagged():
    src = "x = 1\n# vclint: writer-exempt\ny = 2\n"
    with _with_registry({}):
        raw = writercheck.analyze_files([("fix.py", src)])
    got = _codes(finish("fix.py", src, raw))
    assert ("VCL705", 2) in got


# ------------------------------------------------------- VCL710/711

KNOB_FIXTURE = textwrap.dedent('''\
    import os

    A = os.environ.get("VOLCANO_TPU_FIXTURE_DOCUMENTED", "0")
    B = os.environ.get("VOLCANO_TPU_FIXTURE_SECRET", "0")
    ROWS = (
        ("lane", "VOLCANO_TPU_FIXTURE_TABLE"),
    )
    NOT_A_READ = {"VOLCANO_TPU_FIXTURE_KEYED": 1}
''')

KNOB_DOC = textwrap.dedent('''\
    | Variable | Default | Meaning |
    |---|---|---|
    | `VOLCANO_TPU_FIXTURE_DOCUMENTED` | `0` | Covered. |
    | `VOLCANO_TPU_FIXTURE_TABLE` | unset | Covered via tuple table. |
    | `VOLCANO_TPU_FIXTURE_STALE` | `1` | Never read. |
''')


def test_knob_drift_both_directions():
    raw = knobcheck.analyze(
        [("fix.py", KNOB_FIXTURE)], "doc.md", KNOB_DOC)
    got = [(f.code, f.path, f.line) for f in raw]
    # SECRET is read (line 4) but undocumented.
    assert ("VCL710", "fix.py", 4) in got
    # STALE is documented (row line 5) but never read.
    assert ("VCL711", "doc.md", 5) in got
    # DOCUMENTED and the tuple-table TABLE read are matched; the dict
    # key is not a read.
    assert len(got) == 2


def test_knob_doc_only_allowance():
    doc = KNOB_DOC + "| `VOLCANO_TPU_FUZZ_SEEDS` | `64` | Harness. |\n"
    raw = knobcheck.analyze([("fix.py", KNOB_FIXTURE)], "doc.md", doc)
    assert not any(
        f.code == "VCL711" and "FUZZ_SEEDS" in f.message for f in raw)


# ------------------------------------------------- committed tree

def test_registry_resolves_against_committed_tree():
    """Every WRITER_REGISTRY key must name a real function (renames
    must update the registry in the same commit)."""
    sources = [
        (rel, (REPO_ROOT / rel).read_text())
        for rel in writercheck.iter_py_files(REPO_ROOT)
    ]
    raw = writercheck.analyze_files(sources)
    assert not any(
        f.code == "VCL001" and "writer registry" in f.message
        for f in raw
    ), [f.render() for f in raw]


def test_committed_tree_is_writer_and_knob_clean():
    cache = _Sources(REPO_ROOT)
    writer = [f for f in _run_writer(cache) if not f.suppressed]
    assert writer == [], [f.render() for f in writer]
    knob = [f for f in _run_knob(cache) if not f.suppressed]
    assert knob == [], [f.render() for f in knob]
