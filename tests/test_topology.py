"""Topology-aware gang placement tests (ISSUE 20, docs/topology.md):
fabric-plane interning, kernel <-> oracle parity on seeded fragmented
fabrics, the require/prefer constraint semantics through the pregate /
node-order bias / post-solve gate, kill-switch bitwise identity, and
the acceptance e2e — a 32-task require-contiguous gang on a fragmented
2-rack fabric reports topology-infeasible, then binds fully contiguous
after one rebalance cycle plus the eviction grace window with zero
lost pods and budgets held."""

import numpy as np
import pytest

from volcano_tpu.api import (
    FABRIC_RACK,
    FABRIC_SLICE,
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    TOPOLOGY_NONE,
    TOPOLOGY_PREFER,
    TOPOLOGY_REQUIRE,
    TOPOLOGY_ANNOTATION,
    topology_code,
)
from volcano_tpu.cache import ClusterStore, FakeBinder
from volcano_tpu.framework import REBALANCE_SCHEDULER_CONF
from volcano_tpu.metrics import metrics
from volcano_tpu.oracle import oracle_topology
from volcano_tpu.ops import topology as topo
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.sim import ClusterSimulator
from volcano_tpu.synth import fabric_cluster, fabric_labels

ALLOC_CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


def _placements(outcome):
    key = (("outcome", outcome),)
    return metrics.topology_placements.data.get(key, 0.0)


def _gang_pods(store, prefix="fabgang"):
    return [p for p in store.pods.values() if p.name.startswith(prefix)]


def _slice_of(store, node_name):
    n = store.nodes[node_name]
    labels = getattr(n, "labels", None) or getattr(
        getattr(n, "node", None), "labels", {})
    return labels.get(FABRIC_SLICE)


def _pow2(n, floor=1):
    p = floor
    while p < n:
        p *= 2
    return p


# ------------------------------------------------------- kernel parity


def test_kernel_oracle_parity_fixed_seeds():
    """cfit/whole/score/frag planes and the target-block pick agree
    exactly with the Go-shaped oracle on >= 8 seeded fragmented
    fabrics (padding rows sliced off before comparison)."""
    import jax

    for seed in range(10):
        rng = np.random.RandomState(seed)
        N, R, U = 40, 3, 3
        B = int(rng.randint(2, 7))
        idle = rng.uniform(0.0, 8.0, size=(N, R)).astype(np.float32)
        ready = rng.rand(N) > 0.15
        ntasks = rng.randint(0, 6, size=N).astype(np.int32)
        max_tasks = np.where(rng.rand(N) < 0.5,
                             rng.randint(1, 8, size=N), 0).astype(
            np.int32)
        block_id = rng.randint(-1, B, size=N).astype(np.int32)
        prof_req = rng.uniform(0.5, 4.0, size=(U, R)).astype(np.float32)
        prof_req[rng.rand(U, R) < 0.3] = 0.0
        prof_cnt = rng.randint(0, 9, size=U).astype(np.int32)
        eps = np.full(R, 1e-3, np.float32)
        require = bool(seed % 2)

        # Kernel path: pow2-padded axes exactly as _topo_block_fit
        # buckets them; padded nodes are not-ready / blockless, padded
        # profiles request nothing and count zero.
        Np, Upad, Bp = _pow2(N), _pow2(U, 4), _pow2(B, 4)

        def padN(a, n=Np):
            out = np.zeros((n, *a.shape[1:]), a.dtype)
            out[:len(a)] = a
            return out

        bid = np.full(Np, -1, np.int32)
        bid[:N] = block_id
        bf = topo.gang_block_fit(
            padN(idle), padN(ready), padN(ntasks), padN(max_tasks),
            bid, padN(prof_req, Upad), padN(prof_cnt, Upad), eps,
            n_blocks=Bp,
        )
        frag = topo.fabric_frag(bf.cfit, bf.whole, padN(prof_cnt, Upad))
        cfit, whole, score, frag = jax.device_get(
            (bf.cfit, bf.whole, bf.score, frag))
        sel = topo.select_block(whole[:B], score[:B], require)

        ref = oracle_topology(idle, ready, ntasks, max_tasks, block_id,
                              prof_req, prof_cnt, eps, require)
        np.testing.assert_array_equal(
            cfit[:B, :U], ref.cfit, err_msg=f"seed {seed}")
        np.testing.assert_array_equal(
            whole[:B], ref.whole, err_msg=f"seed {seed}")
        np.testing.assert_array_equal(
            score[:B], ref.score, err_msg=f"seed {seed}")
        np.testing.assert_array_equal(
            frag[:B], ref.frag, err_msg=f"seed {seed}")
        assert sel == ref.selected, f"seed {seed}"


def test_select_block_and_bias_edges():
    whole = np.array([False, False])
    score = np.array([3.0, 5.0], np.float32)
    assert topo.select_block(whole, score, require=True) == -1
    assert topo.select_block(whole, score, require=False) == 1
    # Tie -> lowest block id.
    assert topo.select_block(
        np.array([True, True]), np.array([2.0, 2.0], np.float32),
        require=True) == 0
    bias = topo.contig_bias(np.array([0, 1, 0, -1]), 0, 6, weight=2.5)
    np.testing.assert_array_equal(
        bias, np.array([2.5, 0, 2.5, 0, 0, 0], np.float32))
    assert not topo.contig_bias(np.array([0, 1]), -1, 4).any()
    assert not topo.contig_bias(np.array([0, 1]), 0, 4, weight=0.0).any()


# ------------------------------------------------------- fabric planes


def test_fabric_planes_interning_and_cache():
    """Label-derived coordinates intern append-only; unlabeled nodes
    stay blockless; the per-epoch cache invalidates on node churn and
    codes stay stable for surviving rows."""
    store = ClusterStore()
    for i in range(8):
        store.add_node(Node(
            name=f"n{i}", allocatable={"cpu": "4", "memory": "8Gi"},
            labels=fabric_labels(i, nodes_per_host=2, hosts_per_slice=2,
                                 slices_per_rack=2),
        ))
    store.add_node(Node(name="bare",
                        allocatable={"cpu": "4", "memory": "8Gi"}))
    m = store.mirror
    coords, block, n_blocks = topo.fabric_planes(m)
    assert topo.has_fabric(m)
    assert n_blocks == 2  # 8 nodes / 4 per slice
    bare = m.n_row["bare"]
    assert block[bare] == -1 and (coords[bare] == -1).all()
    labeled = [m.n_row[f"n{i}"] for i in range(8)]
    assert sorted(set(block[labeled])) == [0, 1]
    # Same epoch -> cached object identity.
    again = topo.fabric_planes(m)
    assert again[1] is block
    # Node add bumps the epoch; existing codes are stable.
    store.add_node(Node(
        name="n8", allocatable={"cpu": "4", "memory": "8Gi"},
        labels=fabric_labels(8, nodes_per_host=2, hosts_per_slice=2,
                             slices_per_rack=2),
    ))
    coords2, block2, n_blocks2 = topo.fabric_planes(m)
    assert n_blocks2 == 3
    for ni in labeled:
        assert block2[ni] == block[ni]
        assert (coords2[ni] == coords[ni]).all()
    store.close()


def test_topology_code_field_annotation_and_unknown():
    assert topology_code(PodGroup(name="a")) == TOPOLOGY_NONE
    assert topology_code(
        PodGroup(name="b", topology="prefer-contiguous")
    ) == TOPOLOGY_PREFER
    assert topology_code(
        PodGroup(name="c", annotations={
            TOPOLOGY_ANNOTATION: "require-contiguous"})
    ) == TOPOLOGY_REQUIRE
    # The field wins over the annotation; unknown values degrade to
    # unconstrained instead of erroring.
    assert topology_code(
        PodGroup(name="d", topology="prefer-contiguous",
                 annotations={TOPOLOGY_ANNOTATION: "require-contiguous"})
    ) == TOPOLOGY_PREFER
    assert topology_code(
        PodGroup(name="e", topology="ring-of-fire")
    ) == TOPOLOGY_NONE


# ------------------------------------------------- constraint semantics


def test_require_gang_pregated_with_journey_reason():
    """A require-contiguous gang no block can host is held OUT of the
    solve: zero binds, one infeasible transition, and the journey's
    why-pending verdict carries the exclusive drop reason."""
    before = _placements("infeasible")
    store = fabric_cluster(binder=FakeBinder())
    sched = Scheduler(store, conf_str=ALLOC_CONF)
    sched.run_once()
    sched.run_once()  # standing infeasibility: no second count
    assert not any(p.node_name for p in _gang_pods(store))
    assert _placements("infeasible") == before + 1
    if store.journey is not None:
        uid = next(p.uid for p in _gang_pods(store))
        assert "topology-infeasible" in store.journey.why_pending(uid)
    store.close()


def test_require_gang_binds_contiguous_when_block_fits(monkeypatch):
    """With one slice left whole, the require gang binds in one cycle,
    entirely inside one block, and counts a contiguous placement."""
    before = _placements("contiguous")
    store = fabric_cluster(fillers_per_slice=0, gang_tasks=32,
                           binder=FakeBinder())
    sched = Scheduler(store, conf_str=ALLOC_CONF)
    sched.run_once()
    bound = [p for p in _gang_pods(store) if p.node_name]
    assert len(bound) == 32
    assert len({_slice_of(store, p.node_name) for p in bound}) == 1
    assert _placements("contiguous") == before + 1
    store.close()


def test_prefer_gang_scatters_when_no_block_fits():
    """prefer-contiguous never loses binding: on the fragmented fabric
    the gang binds scattered (full-N fallback) and counts scattered."""
    before = _placements("scattered")
    store = fabric_cluster(topology="prefer-contiguous",
                           binder=FakeBinder())
    sched = Scheduler(store, conf_str=ALLOC_CONF)
    sched.run_once()
    bound = [p for p in _gang_pods(store) if p.node_name]
    assert len(bound) == 32
    assert len({_slice_of(store, p.node_name) for p in bound}) > 1
    assert _placements("scattered") == before + 1
    store.close()


def test_prefer_gang_bias_steers_into_whole_block():
    """When a whole block DOES fit the gang, the node-order bias lands
    every task inside it (ties between equal free nodes break toward
    the selected block)."""
    before = _placements("contiguous")
    store = fabric_cluster(fillers_per_slice=0, gang_tasks=32,
                           topology="prefer-contiguous",
                           binder=FakeBinder())
    sched = Scheduler(store, conf_str=ALLOC_CONF)
    sched.run_once()
    bound = [p for p in _gang_pods(store) if p.node_name]
    assert len(bound) == 32
    assert len({_slice_of(store, p.node_name) for p in bound}) == 1
    assert _placements("contiguous") == before + 1
    store.close()


# --------------------------------------------------------- kill switch


def test_kill_switch_bitwise_identity(monkeypatch):
    """VOLCANO_TPU_TOPOLOGY=0 on a constrained store is BYTE-identical
    to an unconstrained store with the feature on: every solve_wave
    call sees the same positional arity (8 — no bias appended) and the
    same bytes in every array leaf, and the end state is bind-for-bind
    identical."""
    import jax

    import volcano_tpu.ops.wave as wave_mod

    real = wave_mod.solve_wave

    def run(store):
        frames = []

        def spy(*args, **kw):
            frames.append((len(args), [
                np.asarray(leaf).tobytes()
                for leaf in jax.tree_util.tree_leaves(args)
            ]))
            return real(*args, **kw)

        monkeypatch.setattr(wave_mod, "solve_wave", spy)
        try:
            Scheduler(store, conf_str=ALLOC_CONF).run_once()
        finally:
            monkeypatch.setattr(wave_mod, "solve_wave", real)
        store.flush_binds()
        binds = dict(store.binder.binds)
        store.close()
        return frames, binds

    monkeypatch.setenv("VOLCANO_TPU_TOPOLOGY", "0")
    frames_off, binds_off = run(fabric_cluster(binder=FakeBinder()))

    monkeypatch.setenv("VOLCANO_TPU_TOPOLOGY", "1")
    frames_plain, binds_plain = run(
        fabric_cluster(topology="", binder=FakeBinder()))

    assert frames_off and frames_off == frames_plain
    assert all(arity == 8 for arity, _ in frames_off)
    assert binds_off and binds_off == binds_plain


def test_unconstrained_store_pays_nothing():
    """A fabric-labeled cluster with NO constrained gang never derives
    block planes on the allocate path (the j_topo.any() gate)."""
    store = fabric_cluster(topology="", binder=FakeBinder())
    sched = Scheduler(store, conf_str=ALLOC_CONF)
    sched.run_once()
    assert getattr(store.mirror, "_fabric_cache", None) is None
    assert sum(1 for p in _gang_pods(store) if p.node_name) == 32
    store.close()


# ------------------------------------------------------- acceptance e2e


def test_e2e_require_contiguous_defrag(monkeypatch):
    """Acceptance: the fragmented 2-rack fabric reports the gang
    topology-infeasible, ONE committed rebalance wave assembles a whole
    slice, and after the grace window the gang binds fully contiguous —
    zero lost pods, per-filler disruption budgets held."""
    monkeypatch.setenv("VOLCANO_TPU_REBALANCE_DRAIN_CAP", "64")
    inf_before = _placements("infeasible")
    cont_before = _placements("contiguous")
    store = fabric_cluster(binder=FakeBinder())
    n_logical = len(store.pods)
    sched = Scheduler(store, conf_str=REBALANCE_SCHEDULER_CONF)
    sim = ClusterSimulator(store, grace_steps=2)

    sched.run_once()  # pregate holds the gang; plan forms + commits
    assert not any(p.node_name for p in _gang_pods(store))
    assert _placements("infeasible") == inf_before + 1
    ledger = store.migrations
    assert ledger is not None and ledger.committed_plans == 1

    converged_cycles = 1
    for _ in range(12):
        converged_cycles += 1
        sim.step()
        sched.run_once()
        if sum(1 for p in _gang_pods(store) if p.node_name) >= 32:
            break
    bound = [p for p in _gang_pods(store) if p.node_name]
    assert len(bound) == 32, f"gang stuck after {converged_cycles}"
    assert len({_slice_of(store, p.node_name) for p in bound}) == 1
    assert _placements("contiguous") == cont_before + 1

    # Zero lost pods: every filler (original or migration-restored) is
    # bound again; nothing disappeared.
    assert len(store.pods) == n_logical
    fillers = [p for p in store.pods.values()
               if p.name.startswith("filler")]
    assert len(fillers) == 8 and all(p.node_name for p in fillers)
    # Budgets: single-member filler groups never exceed 1 disruption.
    for i in range(8):
        assert ledger.disrupted(store, f"default/filler-{i:04d}") <= 1
    assert ledger.committed_plans == 1, "one wave sufficed"
    store.close()


def test_rejected_topology_when_no_drain_helps(monkeypatch):
    """When even a full drain cannot complete any block (the gang is
    bigger than every block's freed capacity), the planner counts
    rejected-topology instead of thrashing evictions."""
    key = (("action", "rebalance"), ("outcome", "rejected-topology"))
    before = metrics.whatif_plans.data.get(key, 0.0)
    # 2 tiny slices of 2 nodes: max 8 slots per block < 12 tasks.
    store = fabric_cluster(racks=2, slices_per_rack=1,
                           nodes_per_slice=2, hosts_per_slice=2,
                           fillers_per_slice=1, gang_tasks=12,
                           binder=FakeBinder())
    sched = Scheduler(store, conf_str=REBALANCE_SCHEDULER_CONF)
    sched.run_once()
    sched.run_once()
    assert store.migrations is None or \
        store.migrations.committed_plans == 0
    assert metrics.whatif_plans.data.get(key, 0.0) > before
    assert not any(p.node_name for p in _gang_pods(store))
    store.close()
