"""Fixture tests for tools/vclint: each analyzer family must catch its
seeded violation at the exact code + location, and the committed tree
must lint clean (the green-gate's first leg).

Tier-1, CPU-only: pure AST analysis, nothing here touches jax.
"""

from __future__ import annotations

import subprocess
import sys
import textwrap
from pathlib import Path

from tools.vclint import hotpath, lockcheck, metricscheck, schemacheck
from tools.vclint.cli import run as vclint_run
from tools.vclint.findings import finish

REPO_ROOT = Path(__file__).resolve().parent.parent


def _codes(findings, path=None):
    return [
        (f.code, f.line) for f in findings
        if not f.suppressed and (path is None or f.path == path)
    ]


# ---------------------------------------------------------------- lock


LOCK_FIXTURE = textwrap.dedent('''\
    import threading


    class Widget:
        def __init__(self):
            self._lock = threading.RLock()
            self._events_lock = threading.Lock()
            self.items = {}  # guarded-by: _lock
            self.trail = []  # guarded-by: _events_lock

        def good_read(self):
            with self._lock:
                return len(self.items)

        def bad_write(self):
            self.items["k"] = 1

        def drain_locked(self):
            return list(self.items)

        def nests(self):
            with self._lock:
                with self._events_lock:
                    self.trail.append(1)

        def inverted(self):
            with self._events_lock:
                with self._lock:
                    return len(self.items)

        # holds: _lock
        def needs_lock(self):
            self.items.clear()

        def forgets(self):
            self.needs_lock()
''')


def test_lock_checker_catches_seeded_violations():
    raw = lockcheck.analyze_files([("fix.py", LOCK_FIXTURE)])
    findings = finish("fix.py", LOCK_FIXTURE, raw)
    got = _codes(findings)
    # bad_write: unguarded write of 'items' (line 16)
    assert ("VCL102", 16) in got
    # forgets: calls needs_lock() without _lock (line 36)
    assert ("VCL105", 36) in got
    # nests() vs inverted(): _lock -> _events_lock AND the reverse
    assert any(c == "VCL103" for c, _l in got)
    # the guarded read via the *_locked method and the with-guarded
    # read produce NO findings
    lines_flagged = {l for _c, l in got}
    assert 13 not in lines_flagged  # good_read body
    assert 19 not in lines_flagged  # drain_locked body
    # needs_lock's own body is covered by its holds declaration
    assert 33 not in lines_flagged


def test_lock_checker_unknown_lock_and_bad_annotation():
    src = textwrap.dedent('''\
        class W:
            def __init__(self):
                self.x = 1  # guarded-by: _ghost_lock
    ''')
    findings = finish("w.py", src, lockcheck.analyze_files([("w.py", src)]))
    assert ("VCL104", 3) in _codes(findings)


def test_suppression_requires_reason():
    src = textwrap.dedent('''\
        import threading


        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self.x = 1  # guarded-by: _lock

            def a(self):
                return self.x  # vclint: disable=VCL101 -- single-writer

            def b(self):
                return self.x  # vclint: disable=VCL101
    ''')
    findings = finish("w.py", src, lockcheck.analyze_files([("w.py", src)]))
    got = _codes(findings)
    # a(): suppressed with a reason -> gone; b(): reasonless -> VCL002
    # hygiene finding AND the original finding stays open.
    assert ("VCL101", 10) not in got
    assert ("VCL002", 13) in got
    assert ("VCL101", 13) in got
    sup = [f for f in findings if f.suppressed]
    assert len(sup) == 1 and sup[0].line == 10
    assert sup[0].reason == "single-writer"


# ------------------------------------------------------------- hot path


HOT_FIXTURE = textwrap.dedent('''\
    from functools import partial

    import jax
    import numpy as np


    @partial(jax.jit, donate_argnums=(0,))
    def scatter(buf, rows, vals):
        return buf.at[rows].set(vals)


    @partial(jax.jit, static_argnames=("mode", "gone"))
    def kernel(x, mode):
        return x * 2


    def hot(buf, rows, vals, x):
        out = solve_fn(x)
        n = float(out)
        buf2 = scatter(buf, rows, vals)
        y = buf + 1
        z = kernel(x, mode=[1, 2])
        fetched = jax.device_get(out)
        ok = float(fetched)
        return n, buf2, y, z, ok
''')


def test_hotpath_checker_catches_seeded_violations():
    raw = hotpath.analyze_file(
        "hot.py", HOT_FIXTURE, [hotpath.HotEntry("hot")]
    )
    findings = finish("hot.py", HOT_FIXTURE, raw)
    got = _codes(findings)
    # float() on the device value (line 19)
    assert ("VCL201", 19) in got
    # read of buf after donation to scatter (line 21)
    assert ("VCL202", 21) in got
    # unhashable static at the call site (line 22)
    assert ("VCL203", 22) in got
    # static_argnames entry 'gone' is not a kernel parameter (def line)
    assert ("VCL203", 13) in got
    # float() on the device_get result is sanctioned (line 24) — the
    # donated-and-reassigned idiom (buf2 = scatter(buf, ...)) too.
    lines = {l for c, l in got if c == "VCL201"}
    assert 24 not in lines


BUDGET_FIXTURE = textwrap.dedent('''\
    from functools import partial

    import jax
    import jax.numpy as jnp


    @partial(jax.jit, static_argnames=("k",))
    def unbudgeted(nodes, prof, k):
        N = nodes.idle.shape[0]
        U = int(prof.req.shape[0])
        tmp = jnp.zeros((N, 64), jnp.float32)
        tmp2 = jnp.ones((U, N), bool)
        small = jnp.zeros((k, 4), jnp.float32)
        return tmp, tmp2, small


    @partial(jax.jit, static_argnames=())
    def registered_ok(nodes):
        N = nodes.idle.shape[0]
        return jnp.zeros((N, 8), jnp.float32)
''')


def test_chunk_budget_checker_catches_full_n_temporaries(monkeypatch):
    # Route the fixture through a budget-checked path name, with
    # `registered_ok` registered (its budget reviewed) and
    # `unbudgeted` not.
    rel = "volcano_tpu/ops/wave.py"
    monkeypatch.setitem(
        hotpath.CHUNK_BUDGET_REGISTRY, rel,
        set(hotpath.CHUNK_BUDGET_REGISTRY[rel]) | {"registered_ok"},
    )
    raw = hotpath.analyze_file(rel, BUDGET_FIXTURE, [])
    findings = finish(rel, BUDGET_FIXTURE, raw)
    got = _codes(findings)
    # The full-N and full-U temporaries of the unregistered jit.
    assert ("VCL204", 11) in got
    assert ("VCL204", 12) in got
    # Static-sized arrays and registered fns stay clean.
    vcl204_lines = {l for c, l in got if c == "VCL204"}
    assert 13 not in vcl204_lines  # (k, 4) is not shape[0]-derived
    assert 21 not in vcl204_lines  # registered_ok is registered


def test_chunk_budget_registry_matches_tree():
    # Registered fns must exist and be jitted in their files — a
    # renamed kernel must update the registry.
    for rel, names in hotpath.CHUNK_BUDGET_REGISTRY.items():
        src = (REPO_ROOT / rel).read_text()
        import ast as _ast

        jits = hotpath.collect_jits(_ast.parse(src))
        for name in names:
            assert name in jits, (rel, name)


def test_hotpath_registry_matches_tree():
    # Every registry entry must resolve to a real function — a renamed
    # lane must update the registry, not silently drop out of analysis.
    for rel, entries in hotpath.HOT_REGISTRY.items():
        src = (REPO_ROOT / rel).read_text()
        raw = hotpath.analyze_file(rel, src, entries)
        missing = [
            f for f in raw
            if f.code == "VCL001" and "not found" in f.message
        ]
        assert not missing, missing


# ------------------------------------------------------- schema <-> ABI


SNAPWIRE_FIX = textwrap.dedent('''\
    import numpy as np

    WIRE_MAGIC = 0x4E534356
    WIRE_VERSION = 1
    WIRE_MAX_DIMS = 8
    _DTYPES = [
        np.dtype(np.float32), np.dtype(np.int32),
    ]
    REC_FULL = 0
    REC_SAME = 1
    REC_DELTA = 2
''')

CC_FIX_DRIFTED = textwrap.dedent('''\
    struct VcsnapDtype { uint8_t code; const char* name; int32_t size; };
    constexpr uint32_t kVcsnapMagic = 0x4E534357u;
    constexpr uint32_t kVcsnapVersion = 1u;
    constexpr int32_t kVcsnapMaxDims = 8;
    constexpr VcsnapDtype kVcsnapDtypes[] = {
        {0, "float32", 4}, {1, "int32", 8},
    };
    constexpr int32_t kVcsnapRecFull = 0;
    constexpr int32_t kVcsnapRecSame = 2;
    constexpr int32_t kVcsnapRecExtra = 7;
''')

SCHEMA_FIX = textwrap.dedent('''\
    from typing import NamedTuple, Tuple

    import numpy as np


    class NodeArrays(NamedTuple):
        idle: np.ndarray
        ready: np.ndarray


    WIRE_COLUMNS: Tuple = (
        ("NodeArrays", "idle", "float32", 2),
        ("NodeArrays", "ready", "float16", 1),
    )
''')

HEADER_FIX = textwrap.dedent('''\
    extern "C" {
    void vcsnap_pack_bits(const int32_t* idx, const int64_t* off,
                          int64_t rows, int32_t words, uint32_t* out);
    }
''')

NATIVE_FIX = textwrap.dedent('''\
    import ctypes

    import numpy as np

    _i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    _i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    _u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")


    def _bind(lib):
        lib.vcsnap_pack_bits.argtypes = [
            _i32p, _i64p, ctypes.c_int64, _u32p,
        ]
        return lib
''')


def test_schema_checker_catches_seeded_drift():
    raw = schemacheck.analyze(
        "sw.py", SNAPWIRE_FIX, "sc.py", SCHEMA_FIX,
        "cc.cc", CC_FIX_DRIFTED, "h.h", HEADER_FIX,
        "nat.py", NATIVE_FIX,
    )
    codes = {f.code for f in raw}
    msgs = "\n".join(f.message for f in raw)
    # int32 declared 8 bytes wide in C++ -> VCL301
    assert "VCL301" in codes and "width 8" in msgs
    # magic differs -> VCL302
    assert "VCL302" in codes and "kVcsnapMagic" in msgs
    # pack_bits bound with 4 argtypes against a 5-param prototype
    assert "VCL303" in codes and "4 argtypes" in msgs
    # float16 is not a wire dtype -> VCL304
    assert "VCL304" in codes and "float16" in msgs
    # Delta record tags (protocol v2, ISSUE 10) -> VCL305: value drift
    # (REC_SAME 1 vs 2), a python tag with no C++ counterpart
    # (REC_DELTA), and a C++ tag with no python counterpart
    # (kVcsnapRecExtra) must each surface.
    assert "VCL305" in codes
    assert "REC_SAME=1 (python) != kVcsnapRecSame=2" in msgs
    assert "REC_DELTA has no C++ counterpart" in msgs
    assert "kVcsnapRecExtra has no python counterpart" in msgs


def test_schema_checker_real_tree_is_clean():
    paths = {
        k: (REPO_ROOT / rel)
        for k, rel in (
            ("snapwire", "volcano_tpu/cache/snapwire.py"),
            ("schema", "volcano_tpu/arrays/schema.py"),
            ("cc", "csrc/vcsnap.cc"),
            ("header", "csrc/vcsnap.h"),
            ("native", "volcano_tpu/native.py"),
        )
    }
    raw = schemacheck.analyze(
        "snapwire", paths["snapwire"].read_text(),
        "schema", paths["schema"].read_text(),
        "cc", paths["cc"].read_text(),
        "header", paths["header"].read_text(),
        "native", paths["native"].read_text(),
    )
    assert raw == [], [f.render() for f in raw]


def test_wire_columns_match_real_encoder_output():
    """WIRE_COLUMNS pins dtype AND ndim of what encode_cluster actually
    produces — the static cross-check verifies table<->NamedTuple and
    table<->wire-dtype-set; this runtime leg closes the loop against
    the producing authority itself."""
    import numpy as np

    from volcano_tpu.api import (
        GROUP_NAME_ANNOTATION, ClusterInfo, JobInfo, Node, NodeInfo,
        Pod, PodGroup, Queue, QueueInfo, TaskInfo,
    )
    from volcano_tpu.arrays.schema import WIRE_COLUMNS, encode_cluster

    cluster = ClusterInfo()
    node = Node(name="n0", allocatable={"cpu": "4", "memory": "8Gi"},
                labels={"zone": "a"})
    cluster.nodes["n0"] = NodeInfo(node)
    cluster.queues["q1"] = QueueInfo(Queue(name="q1", weight=1))
    pg = PodGroup(name="j", namespace="default", min_member=1,
                  queue="q1")
    job = JobInfo(pg.uid)
    job.set_pod_group(pg)
    pod = Pod(uid="p0", name="j-0", namespace="default",
              annotations={GROUP_NAME_ANNOTATION: pg.name},
              containers=[{"cpu": "500m"}],
              node_selector={"zone": "a"})
    ti = TaskInfo(pod)
    job.add_task_info(ti)
    cluster.jobs[pg.uid] = job
    arrays, _maps = encode_cluster(cluster, [ti], [pg.uid])

    produced = {}
    for group in (arrays.nodes, arrays.tasks, arrays.jobs,
                  arrays.queues):
        gname = type(group).__name__
        for fname, value in zip(type(group)._fields, group):
            a = np.asarray(value)
            produced[(gname, fname)] = (a.dtype.name, a.ndim)
    declared = {
        (g, f): (dt, nd) for g, f, dt, nd in WIRE_COLUMNS
    }
    assert set(declared) == set(produced)
    mismatched = {
        k: (declared[k], produced[k])
        for k in declared if declared[k] != produced[k]
    }
    assert not mismatched, mismatched


# ------------------------------------------------------- metrics <-> docs


METRICS_FIX = textwrap.dedent('''\
    import threading


    class _Histogram:
        pass


    class _Gauge:
        pass


    class _Counter:
        pass


    class Metrics:
        def __init__(self):
            ns = "volcano"
            self.solve_latency = _Histogram(
                f"{ns}_solve_latency_ms", "solve latency"
            )
            self.queue_depth = _Gauge(
                f"{ns}_queue_depth", "queue depth"
            )
            self.undocumented = _Counter(
                f"{ns}_brand_new_total", "never made it to the docs"
            )
''')

DOC_FIX_DRIFTED = textwrap.dedent('''\
    # Metrics

    | Metric | Kind | Description |
    |---|---|---|
    | `volcano_solve_latency_ms` | Histogram | solve latency |
    | `volcano_queue_depth` | Counter | documented with the wrong kind |
    | `volcano_ghost_series_total` | Counter | removed from the registry |
''')


def test_metrics_drift_checker_catches_seeded_drift():
    raw = metricscheck.analyze(
        "metrics.py", METRICS_FIX, "metrics.md", DOC_FIX_DRIFTED
    )
    got = [(f.code, f.path, f.line) for f in raw]
    msgs = "\n".join(f.message for f in raw)
    # the registry-only series -> VCL401 at its constructor call
    assert ("VCL401", "metrics.py", 25) in got
    assert "volcano_brand_new_total" in msgs
    # the docs-only series -> VCL402 at its table row
    assert ("VCL402", "metrics.md", 7) in got
    assert "volcano_ghost_series_total" in msgs
    # gauge documented as Counter -> VCL403 at the row
    assert ("VCL403", "metrics.md", 6) in got
    # the in-sync series produces nothing
    assert not any("volcano_solve_latency_ms" in f.message for f in raw)


def test_metrics_drift_real_tree_is_clean():
    raw = metricscheck.analyze(
        "volcano_tpu/metrics/metrics.py",
        (REPO_ROOT / "volcano_tpu/metrics/metrics.py").read_text(),
        "docs/metrics.md",
        (REPO_ROOT / "docs/metrics.md").read_text(),
    )
    assert raw == [], [f.render() for f in raw]


# --------------------------------------- persistent caches (VCL50x)


AGG_FIXTURE = textwrap.dedent('''\
    import numpy as np


    def _epoch_cached(m, attr, key, build):
        return build()


    class Cycle:
        def good_epoch(self, m, Nn, R):
            return _epoch_cached(
                m, "_node_alloc_cache", (m.epoch, Nn, R),
                lambda: (np.zeros((Nn, R)),),
            )

        def bad_epoch(self, m, Nn, R):
            return _epoch_cached(
                m, "_other_cache", (Nn, R),
                lambda: (np.zeros((Nn, R)),),
            )

        def keyed_read(self, store, m, rows):
            cache = getattr(store, "_pending_order_cache", None)
            if cache is not None and cache[0] == m.compact_gen:
                return cache[1]
            store._pending_order_cache = (m.compact_gen, rows)
            return rows

        def keyless_write(self, store, rows):
            store._mystery_cache = rows
''')


def test_aggcheck_catches_seeded_violations():
    from tools.vclint import aggcheck

    raw = aggcheck.analyze_files([("agg.py", AGG_FIXTURE)])
    findings = finish("agg.py", AGG_FIXTURE, raw)
    got = _codes(findings)
    # key tuple without the epoch (bad_epoch's _epoch_cached call).
    assert ("VCL501", 16) in got
    # unregistered persistent cache attribute (keyless_write).
    assert ("VCL503", 29) in got
    assert any("_mystery_cache" in f.message for f in findings
               if f.code == "VCL503")
    # good_epoch's keyed call is clean (only ONE VCL501 in the file).
    assert len([1 for c, _ in got if c == "VCL501"]) == 1
    # Fixture registry entries not present in this file report as
    # stale entries (VCL502) — prove the stale-entry arm fires.
    assert any(c == "VCL502" for c, _ in got)


def test_aggcheck_registry_covers_tree_slots():
    """Every registered slot resolves to real accesses in the scan set
    (no stale registry entries on the committed tree)."""
    from tools.vclint import aggcheck

    sources = [
        (rel, (REPO_ROOT / rel).read_text())
        for rel in aggcheck.SCAN_FILES
    ]
    raw = aggcheck.analyze_files(sources)
    stale = [f for f in raw if "stale" in f.message]
    assert stale == [], [f.render() for f in stale]


# ------------------------------------------------------------- the gate


# ------------------------------------------- anomaly catalog (VCL6xx)


ANOMALY_FIXTURE = textwrap.dedent('''\
    class Auditor:
        def checks(self, anomalies, reason):
            anomalies.append(Anomaly("documented-reason", {"a": 1}))
            anomalies.append(Anomaly("brand-new-reason", {}))
            anomalies.append(Anomaly(reason, {}))
            anomalies.append(Anomaly())
''')

ANOMALY_DOC_FIXTURE = textwrap.dedent("""\
    # Catalog

    | Reason | Meaning | First response |
    |---|---|---|
    | `documented-reason` | fine | none |
    | `ghost-reason` | never emitted | none |
""")


def test_anomalycheck_catches_seeded_drift():
    from tools.vclint import anomalycheck

    raw = anomalycheck.analyze(
        [("audit.py", ANOMALY_FIXTURE)], "obs.md", ANOMALY_DOC_FIXTURE
    )
    got = [(f.code, f.path, f.line) for f in raw]
    msgs = "\n".join(f.message for f in raw)
    # the uncatalogued emit -> VCL601 at the Anomaly() call
    assert ("VCL601", "audit.py", 4) in got
    assert "brand-new-reason" in msgs
    # the docs-only reason -> VCL602 at its table row
    assert ("VCL602", "obs.md", 6) in got
    assert "ghost-reason" in msgs
    # non-literal and missing reasons -> VCL603 at each call
    assert ("VCL603", "audit.py", 5) in got
    assert ("VCL603", "audit.py", 6) in got
    # the in-sync reason produces nothing
    assert not any("documented-reason" in f.message for f in raw)


def test_anomalycheck_real_tree_is_clean():
    from tools.vclint import anomalycheck

    sources = [
        (rel, (REPO_ROOT / rel).read_text())
        for rel in anomalycheck.SCAN_FILES
    ]
    raw = anomalycheck.analyze(
        sources, "docs/observability.md",
        (REPO_ROOT / "docs/observability.md").read_text(),
    )
    assert raw == [], [f.render() for f in raw]


def test_anomalycheck_covers_every_runtime_reason(monkeypatch):
    """Every reason the audit surface can construct at runtime is a
    literal the static scan sees — the catalog check cannot be
    bypassed by an emit path the AST walk misses."""
    from tools.vclint import anomalycheck

    reasons = set()
    for rel in anomalycheck.SCAN_FILES:
        got, findings = anomalycheck.emitted_reasons(
            rel, (REPO_ROOT / rel).read_text())
        assert findings == [], [f.render() for f in findings]
        reasons.update(got)
    # The documented catalog and the emitted set are identical.
    docs = anomalycheck.documented_reasons(
        (REPO_ROOT / "docs/observability.md").read_text())
    assert reasons == set(docs)


def test_vclint_exits_zero_on_committed_tree(tmp_path):
    # Library-level run (what hack/run-checks.sh invokes via -m).
    out = (tmp_path / "out.txt").open("w")
    rc = vclint_run(REPO_ROOT, out=out)
    out.close()
    assert rc == 0, (tmp_path / "out.txt").read_text()


def test_vclint_module_entry_point():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.vclint"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "vclint: 0 finding(s)" in proc.stdout
