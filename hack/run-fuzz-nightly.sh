#!/usr/bin/env bash
# Nightly-depth fuzz: the same eviction-parity families CI runs at 8
# seeds, widened to 150 (or $1) seeds per family.  One CI-runnable
# target so the documented seed count is executable, not aspirational.
set -euo pipefail
cd "$(dirname "$0")/.."
SEEDS="${1:-150}"
export VOLCANO_TPU_FUZZ_SEEDS="$SEEDS"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
exec python -m pytest tests/test_evict_oracle.py tests/test_mirror_fuzz.py \
  -q --no-header "${@:2}"
