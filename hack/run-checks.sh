#!/usr/bin/env bash
# Pre-snapshot green-gate (ISSUE 2): a red lint, a red sanitizer smoke,
# or a red tier-1 suite must never again be the committed state.  Runs:
#
#   1. vclint        — lock discipline, device hot-path hygiene, and
#                      schema<->C++ ABI drift (tools/vclint; exits
#                      nonzero on any unsuppressed finding),
#   2. csrc smoke    — the ASAN + TSAN sanitizer binaries
#                      (make -C csrc test; -Wall -Wextra -Werror build),
#   3. tier-1 pytest — the ROADMAP.md verify line (CPU-only, not slow).
#
# hack/run-e2e.sh runs this first; run it directly before any snapshot.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== [1/4] vclint (static analysis) =="
python -m tools.vclint

echo "== [2/4] csrc sanitizer smoke (ASAN + TSAN, -Werror) =="
make -C csrc test

echo "== [3/4] tier-1 pytest =="
env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider "$@"

echo "== [4/4] lockdep leg (runtime lock enforcement) =="
# The concurrency-heavy suites once more with the annotation-derived
# runtime lockdep armed (obs/lockdep.py): any unguarded access to a
# guarded-by attribute or lock-order inversion lands in the auditor
# ring and fails the run.  Kept to the threaded suites — lockdep is
# process-global once armed, and these are where the races live.
env JAX_PLATFORMS=cpu VOLCANO_TPU_LOCKDEP=1 python -m pytest \
  tests/test_lockdep.py tests/test_shards.py tests/test_solver_pool.py \
  tests/test_pipeline.py -q -p no:cacheprovider -p no:randomly

echo "run-checks: all green"
