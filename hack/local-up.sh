#!/usr/bin/env bash
# Dev-cluster bring-up (the analog of hack/local-up-volcano.sh): starts the
# control plane with the built-in cluster simulator, registers a few nodes,
# and submits the example job.
set -euo pipefail
cd "$(dirname "$0")/.."

PORT="${PORT:-11250}"
python -m volcano_tpu.service --simulate --listen-port "$PORT" &
SVC_PID=$!
trap 'kill $SVC_PID 2>/dev/null || true' EXIT

# Wait for the HTTP server (jax import can take a while on first start).
for _ in $(seq 1 60); do
  curl -fsS "http://127.0.0.1:$PORT/healthz" >/dev/null 2>&1 && break
  sleep 1
done
curl -fsS "http://127.0.0.1:$PORT/healthz" >/dev/null

for i in 0 1 2; do
  curl -fsS -X POST "http://127.0.0.1:$PORT/apis/nodes" \
    -d "{\"name\": \"node-$i\", \"allocatable\": {\"cpu\": \"8\", \"memory\": \"16Gi\"}}" \
    >/dev/null
done

python -m volcano_tpu.cli --server "http://127.0.0.1:$PORT" \
  job run -f examples/job.yaml
sleep 3
python -m volcano_tpu.cli --server "http://127.0.0.1:$PORT" job list
echo "control plane on http://127.0.0.1:$PORT (ctrl-c to stop)"
wait $SVC_PID
