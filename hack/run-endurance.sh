#!/usr/bin/env bash
# Endurance gate (ISSUE 13, docs/observability.md): a compressed-hours
# simulator run — pipelined steady state under sustained churn, node
# flaps, solver-child kills/restarts, preempt waves and pod-table
# compactions — with the runtime conservation auditor ON and SLO
# budgets declared from a calibration window.  Exits nonzero on ANY
# anomaly; the JSON tail carries cycles survived, the anomaly verdict,
# p99s vs budgets, and the measured audit overhead (<2% envelope).
#
# Defaults run the 2k x 20k shape (~minutes on one chip / CPU);
# BENCH_FULL=1 runs the slow 10k x 100k tier.  All BENCH_ENDURANCE_*
# knobs (cycles, churn fraction, delete fraction, budget multiplier)
# and VOLCANO_TPU_AUDIT_SAMPLE pass straight through.
set -euo pipefail
cd "$(dirname "$0")/.."

: "${BENCH_ENDURANCE_CYCLES:=300}"
: "${VOLCANO_TPU_AUDIT_SAMPLE:=16}"
export BENCH_ENDURANCE_CYCLES VOLCANO_TPU_AUDIT_SAMPLE

# The first leg pins the HISTORIC single-connection path regardless of
# how the pool/shard legs below are sized — without the explicit
# pool=1 shards=1 an exported BENCH_ENDURANCE_POOL>=2 or
# BENCH_ENDURANCE_SHARDS>=2 would silently turn this into a second
# pool/shard run and leave the single-connection path ungated.
BENCH_ENDURANCE=1 BENCH_ENDURANCE_POOL=1 BENCH_ENDURANCE_SHARDS=1 \
  python bench.py "$@" | tee /tmp/_vtpu_endurance_single.json
echo "endurance gate OK (0 anomalies)"

# Journey leg (ISSUE 18): the tail's journey block must prove the
# conservation check ran clean over every bound-ish pod (zero
# journey-orphan / journey-incomplete — any violation already failed
# the run above as an anomaly, this asserts the check actually
# EXECUTED over a non-empty set) and the capture overhead stays
# inside the <2%-of-cycle-time envelope.  The gated number is the
# journey's SELF-TIMED capture fraction of the endurance phase
# (journey_direct_pct, the audit-stats idiom): the journey-off A/B
# delta is also reported, but its resolution floor is the host's
# cycle jitter (the audit A/B on the same schedule swings +-5% on a
# loaded CPU host), so a sub-2% effect can't be gated through it
# without flaking.
python - /tmp/_vtpu_endurance_single.json <<'PYEOF'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
tails = [r["endurance"] for r in rows if "endurance" in r]
assert tails, "no endurance tail emitted"
j = tails[0].get("journey")
assert j is not None, "journey block missing from the endurance tail"
assert j["bound_pods_checked"] > 0, j
assert j["conservation_violations"] == 0, j
assert j["events"] > 0 and j["bound"] > 0, j
assert j["ttb_p50_ms"] is not None, j
pct = j["journey_direct_pct"]
assert pct < 2.0, f"journey overhead {pct}% breaches the 2% envelope"
print(f"endurance journey leg OK ({j['bound_pods_checked']} bound pods "
      f"conserved, {j['events']} events, capture {pct}% of cycle time,"
      f" A/B delta {j['journey_overhead_pct']}%)")
PYEOF

# Pool leg (ISSUE 15): the same churn + fault schedule over a 2-replica
# solver pool — kill waves hit RANDOM members while a straggler keeps
# hedges in flight (so kills can land mid-hedge); exits nonzero on any
# anomaly (0 anomalies = conservation held = zero lost pods).  Skip
# with BENCH_ENDURANCE_POOL=1; size with BENCH_ENDURANCE_POOL=<n>.
: "${BENCH_ENDURANCE_POOL:=2}"
export BENCH_ENDURANCE_POOL
if [ "${BENCH_ENDURANCE_POOL}" -gt 1 ]; then
  BENCH_ENDURANCE=1 BENCH_ENDURANCE_SHARDS=1 \
    BENCH_ENDURANCE_CYCLES=$(( BENCH_ENDURANCE_CYCLES / 2 > 150 \
      ? BENCH_ENDURANCE_CYCLES / 2 : 150 )) python bench.py "$@"
  echo "endurance pool leg OK (0 anomalies, pool=${BENCH_ENDURANCE_POOL})"
fi

# Sharded leg (ISSUE 16): the same churn + fault schedule driven by a
# TWO-SHARD control plane over one logical cluster — cross-shard bind
# races resolve through the optimistic commit gate, preempt waves home
# on the evictor shard, and kill waves respawn the shard-0 solver lane.
# Conservation must hold across shard boundaries: exits nonzero on any
# anomaly.  Skip with BENCH_ENDURANCE_SHARDS=1; size with
# BENCH_ENDURANCE_SHARDS=<n> (forces pool=1 — one wire lane per shard).
: "${BENCH_ENDURANCE_SHARDS:=2}"
export BENCH_ENDURANCE_SHARDS
shard_secs=""
if [ "${BENCH_ENDURANCE_SHARDS}" -gt 1 ]; then
  t0=$SECONDS
  BENCH_ENDURANCE=1 \
    BENCH_ENDURANCE_CYCLES=$(( BENCH_ENDURANCE_CYCLES / 2 > 150 \
      ? BENCH_ENDURANCE_CYCLES / 2 : 150 )) python bench.py "$@"
  shard_secs=$(( SECONDS - t0 ))
  echo "endurance shard leg OK (0 anomalies, shards=${BENCH_ENDURANCE_SHARDS})"
fi

# Lockdep leg (ISSUE 17): the shard-leg shape once more with the
# annotation-derived runtime lock enforcement armed
# (VOLCANO_TPU_LOCKDEP=1, obs/lockdep.py) — every guarded-by attribute
# access is checked against the held-lock set and every acquisition
# feeds the process-wide order graph.  Violations land in the auditor
# ring as lockdep-violation / lock-order-cycle anomalies, so the same
# zero-anomaly exit gates them.  The wall-clock delta vs the
# enforcement-off shard leg above is the measured lockdep overhead.
# Skip with BENCH_ENDURANCE_LOCKDEP=0.
: "${BENCH_ENDURANCE_LOCKDEP:=1}"
if [ "${BENCH_ENDURANCE_LOCKDEP}" != "0" ]; then
  t0=$SECONDS
  BENCH_ENDURANCE=1 VOLCANO_TPU_LOCKDEP=1 \
    BENCH_ENDURANCE_CYCLES=$(( BENCH_ENDURANCE_CYCLES / 2 > 150 \
      ? BENCH_ENDURANCE_CYCLES / 2 : 150 )) python bench.py "$@"
  lockdep_secs=$(( SECONDS - t0 ))
  if [ -n "${shard_secs}" ] && [ "${shard_secs}" -gt 0 ]; then
    echo "endurance lockdep leg OK (0 anomalies," \
      "${lockdep_secs}s vs ${shard_secs}s enforcement-off," \
      "overhead $(( (lockdep_secs - shard_secs) * 100 / shard_secs ))%)"
  else
    echo "endurance lockdep leg OK (0 anomalies, ${lockdep_secs}s)"
  fi
fi
