#!/usr/bin/env bash
# Endurance gate (ISSUE 13, docs/observability.md): a compressed-hours
# simulator run — pipelined steady state under sustained churn, node
# flaps, solver-child kills/restarts, preempt waves and pod-table
# compactions — with the runtime conservation auditor ON and SLO
# budgets declared from a calibration window.  Exits nonzero on ANY
# anomaly; the JSON tail carries cycles survived, the anomaly verdict,
# p99s vs budgets, and the measured audit overhead (<2% envelope).
#
# Defaults run the 2k x 20k shape (~minutes on one chip / CPU);
# BENCH_FULL=1 runs the slow 10k x 100k tier.  All BENCH_ENDURANCE_*
# knobs (cycles, churn fraction, delete fraction, budget multiplier)
# and VOLCANO_TPU_AUDIT_SAMPLE pass straight through.
set -euo pipefail
cd "$(dirname "$0")/.."

: "${BENCH_ENDURANCE_CYCLES:=300}"
: "${VOLCANO_TPU_AUDIT_SAMPLE:=16}"
export BENCH_ENDURANCE_CYCLES VOLCANO_TPU_AUDIT_SAMPLE

BENCH_ENDURANCE=1 python bench.py "$@"
echo "endurance gate OK (0 anomalies)"
