#!/usr/bin/env bash
# Endurance gate (ISSUE 13, docs/observability.md): a compressed-hours
# simulator run — pipelined steady state under sustained churn, node
# flaps, solver-child kills/restarts, preempt waves and pod-table
# compactions — with the runtime conservation auditor ON and SLO
# budgets declared from a calibration window.  Exits nonzero on ANY
# anomaly; the JSON tail carries cycles survived, the anomaly verdict,
# p99s vs budgets, and the measured audit overhead (<2% envelope).
#
# Defaults run the 2k x 20k shape (~minutes on one chip / CPU);
# BENCH_FULL=1 runs the slow 10k x 100k tier.  All BENCH_ENDURANCE_*
# knobs (cycles, churn fraction, delete fraction, budget multiplier)
# and VOLCANO_TPU_AUDIT_SAMPLE pass straight through.
set -euo pipefail
cd "$(dirname "$0")/.."

: "${BENCH_ENDURANCE_CYCLES:=300}"
: "${VOLCANO_TPU_AUDIT_SAMPLE:=16}"
export BENCH_ENDURANCE_CYCLES VOLCANO_TPU_AUDIT_SAMPLE

# The first leg pins the HISTORIC single-connection path regardless of
# how the pool/shard legs below are sized — without the explicit
# pool=1 shards=1 an exported BENCH_ENDURANCE_POOL>=2 or
# BENCH_ENDURANCE_SHARDS>=2 would silently turn this into a second
# pool/shard run and leave the single-connection path ungated.
BENCH_ENDURANCE=1 BENCH_ENDURANCE_POOL=1 BENCH_ENDURANCE_SHARDS=1 \
  python bench.py "$@"
echo "endurance gate OK (0 anomalies)"

# Pool leg (ISSUE 15): the same churn + fault schedule over a 2-replica
# solver pool — kill waves hit RANDOM members while a straggler keeps
# hedges in flight (so kills can land mid-hedge); exits nonzero on any
# anomaly (0 anomalies = conservation held = zero lost pods).  Skip
# with BENCH_ENDURANCE_POOL=1; size with BENCH_ENDURANCE_POOL=<n>.
: "${BENCH_ENDURANCE_POOL:=2}"
export BENCH_ENDURANCE_POOL
if [ "${BENCH_ENDURANCE_POOL}" -gt 1 ]; then
  BENCH_ENDURANCE=1 BENCH_ENDURANCE_SHARDS=1 \
    BENCH_ENDURANCE_CYCLES=$(( BENCH_ENDURANCE_CYCLES / 2 > 150 \
      ? BENCH_ENDURANCE_CYCLES / 2 : 150 )) python bench.py "$@"
  echo "endurance pool leg OK (0 anomalies, pool=${BENCH_ENDURANCE_POOL})"
fi

# Sharded leg (ISSUE 16): the same churn + fault schedule driven by a
# TWO-SHARD control plane over one logical cluster — cross-shard bind
# races resolve through the optimistic commit gate, preempt waves home
# on the evictor shard, and kill waves respawn the shard-0 solver lane.
# Conservation must hold across shard boundaries: exits nonzero on any
# anomaly.  Skip with BENCH_ENDURANCE_SHARDS=1; size with
# BENCH_ENDURANCE_SHARDS=<n> (forces pool=1 — one wire lane per shard).
: "${BENCH_ENDURANCE_SHARDS:=2}"
export BENCH_ENDURANCE_SHARDS
shard_secs=""
if [ "${BENCH_ENDURANCE_SHARDS}" -gt 1 ]; then
  t0=$SECONDS
  BENCH_ENDURANCE=1 \
    BENCH_ENDURANCE_CYCLES=$(( BENCH_ENDURANCE_CYCLES / 2 > 150 \
      ? BENCH_ENDURANCE_CYCLES / 2 : 150 )) python bench.py "$@"
  shard_secs=$(( SECONDS - t0 ))
  echo "endurance shard leg OK (0 anomalies, shards=${BENCH_ENDURANCE_SHARDS})"
fi

# Lockdep leg (ISSUE 17): the shard-leg shape once more with the
# annotation-derived runtime lock enforcement armed
# (VOLCANO_TPU_LOCKDEP=1, obs/lockdep.py) — every guarded-by attribute
# access is checked against the held-lock set and every acquisition
# feeds the process-wide order graph.  Violations land in the auditor
# ring as lockdep-violation / lock-order-cycle anomalies, so the same
# zero-anomaly exit gates them.  The wall-clock delta vs the
# enforcement-off shard leg above is the measured lockdep overhead.
# Skip with BENCH_ENDURANCE_LOCKDEP=0.
: "${BENCH_ENDURANCE_LOCKDEP:=1}"
if [ "${BENCH_ENDURANCE_LOCKDEP}" != "0" ]; then
  t0=$SECONDS
  BENCH_ENDURANCE=1 VOLCANO_TPU_LOCKDEP=1 \
    BENCH_ENDURANCE_CYCLES=$(( BENCH_ENDURANCE_CYCLES / 2 > 150 \
      ? BENCH_ENDURANCE_CYCLES / 2 : 150 )) python bench.py "$@"
  lockdep_secs=$(( SECONDS - t0 ))
  if [ -n "${shard_secs}" ] && [ "${shard_secs}" -gt 0 ]; then
    echo "endurance lockdep leg OK (0 anomalies," \
      "${lockdep_secs}s vs ${shard_secs}s enforcement-off," \
      "overhead $(( (lockdep_secs - shard_secs) * 100 / shard_secs ))%)"
  else
    echo "endurance lockdep leg OK (0 anomalies, ${lockdep_secs}s)"
  fi
fi
