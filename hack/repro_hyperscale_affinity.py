#!/usr/bin/env python
"""Minimal repro for the 50k-node x 500k-pod + inter-pod-affinity TPU
worker crash (BASELINE.md known limit).

Runs BASELINE config 5 FULL with affinity, logging every chunked solve
(jobs, rows, active terms, padded count-tensor bytes) to an artifact
JSONL so the crash point is recorded even when the TPU worker dies
mid-solve.  Knobs:

  VOLCANO_TPU_AFF_BUDGET_MB   chunk memory budget (default 1024)
  REPRO_RELEASE=1             aggressively release device state between
                              chunks (delete result refs + clear jax
                              caches every chunk batch) — the "device
                              re-attach" experiment
  REPRO_CYCLES=N              run N full cycles on FRESH stores in one
                              process (default 1).  Round-3 finding: one
                              cycle completes; the historic worker crash
                              reproduces on the SECOND full-scale cycle
                              of the same process (cumulative device
                              state), which is exactly what bench.py's
                              warm+repeat loop does.
  REPRO_NODES / REPRO_PODS    override the 50000 x 500000 shape

Artifact: hack/hyperscale_affinity_repro.jsonl (one line per chunk +
a final status line).  Exit code 0 = completed, nonzero = crashed; the
artifact's last line shows how far it got.

Usage:  python hack/repro_hyperscale_affinity.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

ARTIFACT = os.path.join(os.path.dirname(__file__),
                        "hyperscale_affinity_repro.jsonl")


def main() -> int:
    n_nodes = int(os.environ.get("REPRO_NODES", 50000))
    n_pods = int(os.environ.get("REPRO_PODS", 500000))
    release = os.environ.get("REPRO_RELEASE") == "1"

    art = open(ARTIFACT, "w")

    def emit(rec):
        rec["t"] = round(time.time(), 3)
        art.write(json.dumps(rec) + "\n")
        art.flush()
        os.fsync(art.fileno())
        print(rec, flush=True)

    emit({"event": "start", "nodes": n_nodes, "pods": n_pods,
          "budget_mb": os.environ.get("VOLCANO_TPU_AFF_BUDGET_MB",
                                      "1024"),
          "release": release})

    from volcano_tpu import fastpath
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.synth import synthetic_cluster

    # Instrument the chunker: record every chunk the solver sees.
    orig_chunks = fastpath.FastCycle._solve_chunks
    chunk_no = {"i": 0}

    def chunks_logged(self, solve_jobs, task_rows):
        for cjobs, crows in orig_chunks(self, solve_jobs, task_rows):
            m = self.m
            import numpy as np

            er_a, ei_a = m.c_ip_aff.gather(crows)
            er_n, ei_n = m.c_ip_anti.gather(crows)
            er_s, ei_s, _ = m.c_ip_soft.gather(crows)
            terms = np.concatenate([ei_a, ei_n, ei_s])
            E = len(np.unique(terms)) if len(terms) else 0
            D = max(1, len(m.domains))
            from volcano_tpu.ops.wave import bucket_pow2

            cost = float(bucket_pow2(E, floor=1)) * D * 8.0 if E else 0.0
            chunk_no["i"] += 1
            emit({"event": "chunk", "n": chunk_no["i"],
                  "jobs": len(cjobs), "rows": int(len(crows)),
                  "active_terms": int(E), "domains": int(D),
                  "count_tensor_mb": round(cost / 1e6, 1)})
            yield cjobs, crows
            emit({"event": "chunk_done", "n": chunk_no["i"]})
            if release:
                import gc

                import jax

                gc.collect()
                jax.clear_caches()
                emit({"event": "released", "n": chunk_no["i"]})

    fastpath.FastCycle._solve_chunks = chunks_logged

    n_cycles = int(os.environ.get("REPRO_CYCLES", 1))
    for cyc in range(n_cycles):
        emit({"event": "build_store", "cycle": cyc})
        store = synthetic_cluster(
            n_nodes=n_nodes, n_pods=n_pods, gang_size=8, zones=16,
            affinity_fraction=0.05, anti_affinity_fraction=0.05,
            spread_fraction=0.1, seed=cyc,
        )
        store.async_bind = True
        emit({"event": "cycle_start", "cycle": cyc})
        t0 = time.perf_counter()
        try:
            Scheduler(store).run_once()
        except BaseException as e:  # noqa: BLE001 — record then re-raise
            emit({"event": "crash", "cycle": cyc,
                  "error": repr(e)[:500],
                  "after_s": round(time.perf_counter() - t0, 1),
                  "chunks_done": chunk_no["i"]})
            raise
        store.flush_binds()
        bound = sum(1 for p in store.pods.values() if p.node_name)
        emit({"event": "done", "cycle": cyc,
              "cycle_s": round(time.perf_counter() - t0, 1),
              "bound": bound, "chunks": chunk_no["i"]})
        store.close()
        del store
        if release:
            import gc

            import jax

            gc.collect()
            jax.clear_caches()
            emit({"event": "released", "cycle": cyc})
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
