"""Profile the host lanes (derive/order/encode/commit/close/enqueue —
plus feed on pipelined stores) of one north-star cycle (10k nodes x
100k pods, plain) under cProfile.

The device lane dominates wall-clock but is excluded from analysis; the
point is the per-function split of the ~350 ms of host work VERDICT r3
flagged.  Run on the real chip (default platform) so chunking and shapes
match the bench exactly:

    python hack/profile_host_lanes.py [n_nodes n_pods]

Env: PROF_SORT=cumulative|tottime (default tottime), PROF_LINES=40.
"""

import cProfile
import os
import pstats
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from volcano_tpu.scheduler import Scheduler  # noqa: E402
from volcano_tpu.synth import synthetic_cluster  # noqa: E402

CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


def main():
    n_nodes = int(sys.argv[1]) if len(sys.argv) > 1 else 10000
    n_pods = int(sys.argv[2]) if len(sys.argv) > 2 else 100000
    mk = lambda seed: synthetic_cluster(
        n_nodes=n_nodes, n_pods=n_pods, gang_size=8, zones=16, seed=seed
    )
    # Warm-up: compile + populate jit caches.
    store = mk(0)
    store.async_bind = True
    t0 = time.perf_counter()
    Scheduler(store, conf_str=CONF).run_once()
    print(f"warm cycle {time.perf_counter() - t0:.2f}s", file=sys.stderr)
    store.flush_binds()
    store.close()

    store = mk(1)
    store.async_bind = True
    sched = Scheduler(store, conf_str=CONF)
    prof = cProfile.Profile()
    t0 = time.perf_counter()
    prof.enable()
    sched.run_once()
    prof.disable()
    dt = time.perf_counter() - t0
    lanes = getattr(store, "last_cycle_lanes", None) or {}
    lane_s = " ".join(
        f"{k}={v * 1e3:.0f}ms"
        for k, v in sorted(lanes.items(), key=lambda kv: -kv[1])
    )
    print(f"profiled cycle {dt * 1e3:.0f}ms  lanes[{lane_s}]", file=sys.stderr)
    store.flush_binds()
    store.close()

    st = pstats.Stats(prof)
    st.sort_stats(os.environ.get("PROF_SORT", "tottime"))
    st.print_stats(int(os.environ.get("PROF_LINES", 40)))


if __name__ == "__main__":
    main()
