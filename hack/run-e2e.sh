#!/usr/bin/env bash
# E2E suite against the simulated cluster (the rebuild's kind analog,
# hack/run-e2e-kind.sh): full control-plane + scheduler + fake kubelet.
set -euo pipefail
cd "$(dirname "$0")/.."
# Green-gate first (ISSUE 2): vclint + csrc ASAN/TSAN smoke + tier-1
# suite — the e2e pass below must never run on a red tree.
hack/run-checks.sh
# The pipelined-mode pass (tests/test_pipeline.py: double-buffered
# sessions over the remote-solver split, overlap-correctness gate) runs
# inside run-checks.sh's tier-1 leg above — not repeated here.
exec python -m pytest tests/test_scheduler_e2e.py tests/test_controllers.py \
  tests/test_admission_cli.py tests/test_examples.py \
  tests/test_remote_solver.py tests/test_rendezvous_e2e.py -q "$@"
