#!/usr/bin/env bash
# E2E suite against the simulated cluster (the rebuild's kind analog,
# hack/run-e2e-kind.sh): full control-plane + scheduler + fake kubelet.
set -euo pipefail
cd "$(dirname "$0")/.."
# Green-gate first (ISSUE 2): vclint + csrc ASAN/TSAN smoke + tier-1
# suite — the e2e pass below must never run on a red tree.
hack/run-checks.sh
# The pipelined-mode pass (tests/test_pipeline.py: double-buffered
# sessions over the remote-solver split, overlap-correctness gate) runs
# inside run-checks.sh's tier-1 leg above — not repeated here.
# BENCH_MESH smoke (ISSUE 7): the mesh-native sharded solve A/B on a
# forced 4-device virtual-CPU host at a small shape — asserts the mesh
# pass completes, pipelines, and emits its JSON tail (plain vs mesh,
# lane splits, winner-reduce microbench).
BENCH_MESH=4 BENCH_CONFIG=2 BENCH_NODES=256 BENCH_PODS=2048 \
  BENCH_REPEATS=1 BENCH_PIPE_CYCLES=5 JAX_PLATFORMS=cpu \
  python bench.py
# BENCH_HOST smoke (ISSUE 8): the incremental host-lane A/B at a small
# shape — asserts all three modes (on / off / dirty-cap fallback)
# complete, pipeline, and emit their host_lanes_ms JSON tails.
BENCH_HOST=1 BENCH_CONFIG=2 BENCH_NODES=128 BENCH_PODS=1024 \
  BENCH_REPEATS=1 BENCH_PIPE_CYCLES=5 JAX_PLATFORMS=cpu \
  python bench.py | python -c '
import json, sys
rows = [json.loads(l) for l in sys.stdin if l.strip()]
want = {"(incremental on)", "(incremental off)", "(incremental fallback)"}
modes = {m for m in want for r in rows if m in r["metric"]}
assert modes == want, f"missing BENCH_HOST modes: {want - modes}"
assert any("host_lanes_ms" in r for r in rows), "no host_lanes_ms tail"
print(f"BENCH_HOST smoke OK ({len(rows)} rows)")
'
# BENCH_DEVINCR smoke (ISSUE 9): the device-lane incremental A/B at a
# small shape — asserts all three modes (on / off / dirty-cap
# forced-fallback) complete, pipeline, and emit their devincr JSON
# tails, the on/fallback passes actually take their warm/full paths,
# and the null-delta probe completes WITHOUT a solve dispatch when the
# lane is on.
BENCH_DEVINCR=1 BENCH_CONFIG=2 BENCH_NODES=128 BENCH_PODS=1024 \
  BENCH_REPEATS=1 BENCH_PIPE_CYCLES=5 JAX_PLATFORMS=cpu \
  python bench.py | python -c '
import json, sys
rows = [json.loads(l) for l in sys.stdin if l.strip()]
want = {"(devincr on)", "(devincr off)", "(devincr fallback)"}
modes = {m for m in want for r in rows if m in r["metric"]}
assert modes == want, f"missing BENCH_DEVINCR modes: {want - modes}"
tails = {m: r["devincr"] for m in want for r in rows
         if m in r["metric"] and "devincr" in r}
assert tails["(devincr on)"]["warm"] >= 1, tails
assert tails["(devincr on)"]["null_delta_dispatches"] == 0, tails
assert tails["(devincr on)"]["null_delta_skips"] >= 1, tails
assert tails["(devincr fallback)"]["warm"] == 0, tails
assert tails["(devincr fallback)"]["full"] >= 1, tails
assert tails["(devincr off)"]["null_delta_dispatches"] >= 1, tails
print(f"BENCH_DEVINCR smoke OK ({len(rows)} rows)")
'
# BENCH_WIRE smoke (ISSUE 10): the remote-solver transport A/B at a
# small shape — asserts all three modes (delta / full / forced
# fallback) complete over real loopback TCP with the 5%-churn
# pipelined feed and emit their wire JSON tails, the delta pass
# actually ships delta frames for FEWER bytes/cycle than full frames,
# and the fallback pass counts its forced full-frame fallbacks.
BENCH_WIRE=1 BENCH_CONFIG=2 BENCH_NODES=128 BENCH_PODS=1024 \
  BENCH_REPEATS=1 BENCH_PIPE_CYCLES=5 JAX_PLATFORMS=cpu \
  python bench.py | python -c '
import json, sys
rows = [json.loads(l) for l in sys.stdin if l.strip()]
want = {"(wire delta)", "(wire full)", "(wire fallback)"}
modes = {m for m in want for r in rows if m in r["metric"]}
assert modes == want, f"missing BENCH_WIRE modes: {want - modes}"
tails = {m: r["wire"] for m in want for r in rows
         if m in r["metric"] and "wire" in r}
assert tails["(wire delta)"]["frames"]["delta"] >= 1, tails
assert tails["(wire full)"]["frames"]["delta"] == 0, tails
assert tails["(wire fallback)"]["frames"]["delta"] == 0, tails
assert tails["(wire fallback)"]["fallbacks"].get("forced", 0) >= 1, tails
ratio = tails["(wire full)"]["bytes_per_cycle"] / max(
    tails["(wire delta)"]["bytes_per_cycle"], 1)
assert ratio > 2, f"delta frames did not shrink the wire: {ratio:.1f}x"
print(f"BENCH_WIRE smoke OK ({len(rows)} rows, {ratio:.1f}x fewer "
      "bytes/cycle on deltas)")
'
# BENCH_POOL smoke (ISSUE 15): the solver replica pool A/B at a small
# shape under the injected straggler + kill schedule — asserts pool=2
# hedging cuts the device-lane p99 >= 20% vs pool=1, the mid-stream
# replica kill heals with deltas re-engaged (post-restart full frame
# then deltas on the killed replica) at the cost of at most one
# cycle's lost-reply re-place, and zero pods are lost (0 anomalies).
BENCH_POOL=1 BENCH_NODES=128 BENCH_PODS=1024 BENCH_POOL_CYCLES=24 \
  BENCH_POOL_SIZES=1,2 JAX_PLATFORMS=cpu \
  python bench.py | python -c '
import json, sys
rows = [json.loads(l) for l in sys.stdin if l.strip()]
tails = {r["pool"]["size"]: r["pool"] for r in rows if "pool" in r}
assert set(tails) == {1, 2}, f"missing pool sizes: {sorted(tails)}"
p1, p2 = tails[1], tails[2]
assert p2["hedge_dispatches"] >= 1, p2
assert p2["hedge_wins"] >= 1, p2
assert p2["device_p99_ms"] <= 0.8 * p1["device_p99_ms"], (
    "hedging did not cut device p99 >= 20%%: pool1=%s pool2=%s"
    % (p1["device_p99_ms"], p2["device_p99_ms"]))
for size, t in tails.items():
    assert t["lost_pods"] == 0, f"pool={size} lost pods: {t}"
    assert t["anomalies"] == 0, f"pool={size} anomalies: {t}"
    # The killed replica healed: its post-restart stream is a full
    # frame followed by re-engaged deltas.
    pk = t["post_kill_frames"]
    assert pk.get("full", 0) >= 1 and pk.get("delta", 0) >= 1, t
assert p2["failovers"] + p2["lost_reply_rows"] >= 1, p2
cut = 100 * (1 - p2["device_p99_ms"] / p1["device_p99_ms"])
print("BENCH_POOL smoke OK (device p99 %.0fms -> %.0fms, %.0f%% cut, "
      "%s hedges / %s wins)" % (p1["device_p99_ms"], p2["device_p99_ms"],
                                cut, p2["hedge_dispatches"],
                                p2["hedge_wins"]))
'
# BENCH_SHARDS smoke (ISSUE 16): the sharded control plane A/B at a
# small shape — asserts shards=2 actually engages (both shards run
# cycles and bind), the drain phase binds the SAME total as shards=1
# with ZERO cross-shard conflicts on the zone-partitioned workload,
# and the contention-heavy phase resolves its forced same-node races
# with zero lost pods and the conservation auditor clean.
BENCH_SHARDS=1,2 BENCH_NODES=32 BENCH_PODS=192 BENCH_SHARDS_SECS=4 \
  BENCH_SHARDS_SOLVE_MS=25 JAX_PLATFORMS=cpu \
  python bench.py | python -c '
import json, sys
rows = [json.loads(l) for l in sys.stdin if l.strip()]
tails = {r["shards"]["shards"]: r["shards"] for r in rows
         if "shards" in r}
assert set(tails) == {1, 2}, f"missing shard sizes: {sorted(tails)}"
s1, s2 = tails[1], tails[2]
# shards=2 engaged: both shards ran cycles and bound pods.
per = s2["per_shard"]
assert set(per) == {"s0", "s1"}, per
assert all(v["cycles"] >= 1 for v in per.values()), per
assert sum(v["binds"] for v in per.values()) >= 1, per
# Conflict-free partition: same bind total as shards=1, gate quiet.
assert s2["drain"]["bound"] == s1["drain"]["bound"], (s1, s2)
assert s1["drain"]["conflicts"] == 0, s1
assert s2["drain"]["conflicts"] == 0, s2
assert s2["throughput_conflicts"] == 0, s2
for size, t in tails.items():
    assert t["lost_pods"] == 0, f"shards={size} lost pods: {t}"
    assert t["anomalies"] == 0, f"shards={size} anomalies: {t}"
    c = t["contention"]
    assert c["lost_pods"] == 0, f"shards={size} contention lost: {c}"
    assert c["anomalies"] == 0, f"shards={size} contention anoms: {c}"
# The contention phase actually raced across shards.
assert s2["contention"]["conflicts"] >= 1, s2
print("BENCH_SHARDS smoke OK (%s -> %s binds/sec, %.2fx, "
      "%s contention conflicts, 0 lost)"
      % (s1["binds_per_sec"], s2["binds_per_sec"],
         s2["speedup_vs_shard1"], s2["contention"]["conflicts"]))
'
# BENCH_TOPOLOGY smoke (ISSUE 20): topology-aware gang placement on a
# fragmented 2-rack fabric — asserts the pregate held the
# require-contiguous gang exactly once (topology-infeasible), one
# slice-defrag plan committed, the gang converged FULLY contiguous
# (every member in one fabric block), and zero pods were lost (every
# drained filler re-bound).
BENCH_TOPOLOGY=1 JAX_PLATFORMS=cpu python bench.py | python -c '
import json, sys
rows = [json.loads(l) for l in sys.stdin if l.strip()]
tails = [r["topology"] for r in rows if "topology" in r]
assert tails, "no topology tail emitted"
t = tails[0]
assert t["infeasible_transitions"] == 1, f"pregate never held: {t}"
assert t["committed_plans"] >= 1, f"defrag never committed: {t}"
assert t["fit_before"] < 1.0, f"fabric was not fragmented: {t}"
assert t["contiguity_after"] == 1.0, f"gang not contiguous: {t}"
assert t["contiguous_placements"] >= 1, t
assert t["evictions"] >= 1, t
assert t["lost_pods"] == 0, f"pods lost: {t}"
print("BENCH_TOPOLOGY smoke OK (fit %.3f -> contiguity %.3f, "
      "%s evictions, %s cycles)"
      % (t["fit_before"], t["contiguity_after"], t["evictions"],
         t["converged_cycles"]))
'
# BENCH_PREEMPT smoke (ISSUE 11): the device-native preempt lane on a
# small fragmented-priority cluster — asserts the DEVICE lane actually
# engaged (a committed what-if plan + evictions through the shared
# ledger), the serving gang bound, and zero pods were lost (every
# evicted batch pod restored as Pending and re-placed or parked).
BENCH_PREEMPT=1 BENCH_NODES=8 JAX_PLATFORMS=cpu \
  VOLCANO_TPU_EVICT_DEVICE=1 python bench.py | python -c '
import json, sys
rows = [json.loads(l) for l in sys.stdin if l.strip()]
tails = [r["preempt"] for r in rows if "preempt" in r]
assert tails, "no preempt tail emitted"
t = tails[0]
assert t["committed_plans"] >= 1, f"device lane never committed: {t}"
assert t["plans"].get("preempt/committed", 0) >= 1, t
assert t["evictions"] >= 1, t
assert t["gang_bound"] >= t["gang"], f"serving gang did not bind: {t}"
assert t["lost_pods"] == 0, f"pods lost: {t}"
assert t["restored"] == t["evictions"], t
# (%-formatting: a backslash inside an f-string expression is a
# SyntaxError before Python 3.12.)
print("BENCH_PREEMPT smoke OK (%s evictions, %s cycles to bind)"
      % (t["evictions"], t["converged_cycles"]))
'
# BENCH_COMPOSED smoke (ISSUE 12): every fast lane engaged TOGETHER —
# virtual 4-device mesh + devincr + incremental host lanes + pipelining
# + 5% churn — in one run.  Asserts the composed tail proves engagement
# of every lane (mesh shards > 1, devincr warm counted, null-delta
# skips with ZERO dispatches, incremental derives in delta mode) and
# that the composed pipelined cycle beats the plain pass.
BENCH_COMPOSED=1 BENCH_COMPOSED_MESH=4 BENCH_NODES=256 BENCH_PODS=2048 \
  BENCH_REPEATS=1 BENCH_PIPE_CYCLES=5 JAX_PLATFORMS=cpu \
  python bench.py | python -c '
import json, sys
rows = [json.loads(l) for l in sys.stdin if l.strip()]
comp = [r for r in rows if "composed" in r]
assert comp, "no composed tail emitted"
r = comp[0]
c = r["composed"]
assert c["mesh_shards"] > 1, c
assert c["pipelined_ms"] < c["plain_ms"], c
assert c["incremental_derives"].get("delta", 0) >= 1, c
dv = r["devincr"]
assert dv["warm"] >= 1, dv
assert dv["null_delta_dispatches"] == 0, dv
assert dv["null_delta_skips"] >= 1, dv
assert "compile_ms" in r and "warmup_cycles_ms" in r, sorted(r)
print("BENCH_COMPOSED smoke OK (%sms plain -> %sms composed, "
      "%s shards)" % (c["plain_ms"], c["pipelined_ms"],
                      c["mesh_shards"]))
'
# Composed bind parity (ISSUE 12): the everything-on configuration
# (mesh + devincr + incremental + pipelining) must land bit-for-bit
# the same binds as the everything-off configuration once both reach
# quiescence on the same seeded backlog.
JAX_PLATFORMS=cpu python -c '
from volcano_tpu.virtualcpu import force_virtual_cpu_platform
force_virtual_cpu_platform(4)
import os
from volcano_tpu.parallel import make_mesh
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.synth import synthetic_cluster

def run(on):
    os.environ.update({
        "VOLCANO_TPU_DEVINCR": "1" if on else "0",
        "VOLCANO_TPU_INCREMENTAL": "1" if on else "0",
        "VOLCANO_TPU_TWOPHASE": "1" if on else "0",
    })
    store = synthetic_cluster(n_nodes=256, n_pods=2048, gang_size=4,
                              zones=4, seed=9)
    if on:
        store.pipeline = True
        store.solve_mesh = make_mesh(4, platform="cpu")
    sched = Scheduler(store)
    for _ in range(4 if on else 2):
        sched.run_once()
    store.flush_binds()
    binds = {p.name: p.node_name for p in store.pods.values()}
    assert all(binds.values()), "backlog did not fully bind"
    store.close()
    return binds

on = run(True)
off = run(False)
assert on == off, "composed binds differ from the everything-off run"
print(f"composed bind parity OK ({len(on)} pods bit-for-bit)")
'
# Endurance smoke (ISSUE 13 + the ISSUE 15 pool leg): >= 200 churn
# cycles at a small shape with the full fault schedule — mid-run
# kill/restarts of RANDOM solver-pool members (a straggler + tight
# hedge knobs keep hedges in flight, so kills can land mid-hedge),
# node flaps, preempt waves, and enough lifecycle churn to force at
# least one real pod-table compaction — auditors on every cycle.  The
# gate exits nonzero on any anomaly; the tail assertion additionally
# proves the faults actually fired and the audit verdict is clean
# (0 anomalies = conservation held = zero lost pods).
BENCH_ENDURANCE=1 BENCH_ENDURANCE_POOL=2 BENCH_NODES=64 BENCH_PODS=1024 \
  BENCH_ENDURANCE_CYCLES=200 BENCH_ENDURANCE_DELETE_FRAC=0.03 \
  VOLCANO_TPU_AUDIT_SAMPLE=8 JAX_PLATFORMS=cpu \
  python bench.py | python -c '
import json, sys
rows = [json.loads(l) for l in sys.stdin if l.strip()]
tails = [r["endurance"] for r in rows if "endurance" in r]
assert tails, "no endurance tail emitted"
e = tails[0]
assert e["anomalies"] == 0, f"endurance anomalies: {e}"
assert e["cycles"] >= 200, e
assert e["solver_kills"] >= 1, f"no solver kill exercised: {e}"
assert e["compactions"] >= 1, f"no compaction exercised: {e}"
assert e["node_flaps"] >= 1 and e["preempt_waves"] >= 1, e
p = e.get("pool")
assert p and p["size"] == 2, f"pool leg did not engage: {e}"
assert p["hedge_dispatches"] >= 1, f"no hedge exercised: {p}"
audits = [r["audit"] for r in rows if "audit" in r]
assert audits and audits[0]["sampled_cycles"] >= 1, audits
c, k, n = e["cycles"], e["solver_kills"], e["compactions"]
h = p["hedge_dispatches"]
print(f"endurance smoke OK ({c} cycles, {k} pool-member kills, "
      f"{h} hedges, {n} compactions, 0 anomalies)")
'
# Journey smoke (ISSUE 18): /debug/pods/<uid> + the /debug/health
# journey rollup on a TWO-SHARD store mid-churn — the stitched
# cross-shard timeline and the why-pending verdict must serve while
# the shards are still re-pending and re-binding the backlog, and the
# conservation check over every bound pod must come back empty.
JAX_PLATFORMS=cpu python - <<'PYEOF'
import json, urllib.request
import numpy as np
from volcano_tpu.api import TaskStatus
from volcano_tpu.service import Service
from volcano_tpu.shard import ShardedScheduler
from volcano_tpu.synth import synthetic_cluster

ST_BOUND = int(TaskStatus.Bound)
store = synthetic_cluster(n_nodes=16, n_pods=96, gang_size=4,
                          n_queues=4, seed=7)
store.pipeline = True

def feed(fc):
    m = fc.m
    rows = np.flatnonzero(
        (m.p_status[:fc.Pn] == ST_BOUND) & m.p_alive[:fc.Pn])
    if len(rows):
        fc._unbind_rows(rows[: max(1, len(rows) // 4)])

store.cycle_feed = feed
sched = ShardedScheduler(store, shards=2)
svc = Service(store=store, schedule_period=30.0, controller_period=5.0)
port = svc.start(http_port=0)

def get(path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return json.loads(r.read())

def bound_uids():
    with store._lock:
        m = store.mirror
        return [m.p_uid[i] for i in range(len(m.p_uid))
                if m.p_alive[i] and m.p_uid[i]
                and int(m.p_status[i]) == ST_BOUND]

try:
    for i in range(12):
        sched.run_once()
        if i == 6:
            # Mid-churn scrape: half the backlog is in flight right now.
            uid = bound_uids()[0]
            tl = get(f"/debug/pods/{uid}")
            assert tl["uid"] == uid and tl["events"], tl
            assert tl["events"][0]["kind"] == "enqueued", tl["events"][0]
            assert "why_pending" in tl, sorted(tl)
            roll = get("/debug/health")["journey"]
            assert roll["pods_tracked"] > 0, roll
            assert any(q["bound_total"] > 0
                       for q in roll["queues"].values()), roll
    store.flush_binds()
    bound = bound_uids()
    anoms = store.journey.conservation_check(bound)
    assert not anoms, [a.to_dict() for a in anoms]
    print(f"journey smoke OK (2 shards, {len(bound)} bound pods, "
          "mid-churn /debug/pods served, conservation clean)")
finally:
    svc.stop()
    store.close()
PYEOF
exec python -m pytest tests/test_scheduler_e2e.py tests/test_controllers.py \
  tests/test_admission_cli.py tests/test_examples.py \
  tests/test_remote_solver.py tests/test_rendezvous_e2e.py -q "$@"
