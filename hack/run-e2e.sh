#!/usr/bin/env bash
# E2E suite against the simulated cluster (the rebuild's kind analog,
# hack/run-e2e-kind.sh): full control-plane + scheduler + fake kubelet.
set -euo pipefail
cd "$(dirname "$0")/.."
python -m pytest tests/test_scheduler_e2e.py tests/test_controllers.py \
  tests/test_admission_cli.py tests/test_examples.py \
  tests/test_remote_solver.py tests/test_rendezvous_e2e.py -q "$@"
# Pipelined-mode pass: double-buffered sessions over the remote-solver
# split (two real OS processes, frame N+1 sent while frame N's reply is
# in flight) plus the tier-1 overlap-correctness gate.  Runs under
# JAX_PLATFORMS=cpu — no TPU required (tier1 marker, pyproject.toml).
exec python -m pytest tests/test_pipeline.py -q "$@"
